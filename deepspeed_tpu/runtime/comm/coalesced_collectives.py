"""Quantized / coalesced collectives (ZeRO++ analog).

Analog of ``deepspeed/runtime/comm/coalesced_collectives.py``
(``reduce_scatter_coalesced:81``, ``all_to_all_quant_reduce:31`` = qgZ) and
the qwZ quantized-weight allgather (``partition_parameters.py:753
CUDAQuantizer``). Collectives run inside ``shard_map`` over the ``data``
axis; quantization uses the Pallas block kernels (``ops/pallas/quantizer``),
so the wire format is int8 + fp32 group scales — 4x less ICI/DCN traffic
than fp32, 2x less than bf16.
"""

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...utils import groups


def quantize_int8(x, group_size: int = 256):
    """jnp block quantizer — same math as ``ops/pallas/quantizer`` but usable
    inside shard_map manual regions (pallas_call needs vma annotations there;
    XLA fuses this to the same kernel shape anyway)."""
    flat = x.reshape(-1, group_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(flat), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-10) / 127.0
    q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def dequantize_int8(q, scales, orig_dtype=jnp.float32, group_size: int = 256):
    flat = q.reshape(-1, group_size).astype(jnp.float32) * scales
    return flat.reshape(q.shape).astype(orig_dtype)


def _flatten_concat(tensors: Sequence[jnp.ndarray]):
    flats = [t.reshape(-1) for t in tensors]
    sizes = [f.size for f in flats]
    return jnp.concatenate(flats), sizes


def _unflatten(flat, sizes, shapes):
    out, off = [], 0
    for n, s in zip(sizes, shapes):
        out.append(flat[off:off + n].reshape(s))
        off += n
    return out


def reduce_scatter_coalesced(tensors: List[jnp.ndarray], axis_name: str = "data"):
    """Flatten a tensor list and reduce-scatter once over the axis
    (reference ``:81``). Inside shard_map: returns this rank's reduced shard."""
    flat, sizes = _flatten_concat(tensors)
    n = jax.lax.axis_size(axis_name)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True), sizes


def quantized_reduce_scatter(x, axis_name: str = "data", group_size: int = 256):
    """qgZ-style gradient reduction (inside shard_map): each rank quantizes
    its n chunks to int8, all-to-alls them, dequantizes and reduces locally.
    Comm volume: int8 + scales instead of fp32. Returns the reduced shard."""
    n = jax.lax.axis_size(axis_name)
    pad = (-x.size) % (n * group_size)
    flat = jnp.concatenate([x.reshape(-1), jnp.zeros((pad,), x.dtype)]) if pad else x.reshape(-1)
    chunks = flat.reshape(n, -1)                     # chunk i → rank i
    q, scales = quantize_int8(chunks, group_size)    # (n, C) int8, (n*C/gs, 1)
    scales = scales.reshape(n, -1)
    # exchange: rank r receives chunk r from every peer
    q_x = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    s_x = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = dequantize_int8(q_x.reshape(n, -1, group_size).reshape(n, -1),
                          s_x.reshape(-1, 1), jnp.float32, group_size).reshape(n, -1)
    return jnp.sum(deq, axis=0)                      # reduced shard of this rank


def quantized_all_gather(shard, axis_name: str = "data", group_size: int = 256,
                         out_dtype=jnp.float32):
    """qwZ-style weight allgather (inside shard_map): quantize the local
    shard, all-gather int8 + scales, dequantize — 4x less gather traffic
    (reference zero_quantized_weights, engine.py:901)."""
    pad = (-shard.size) % group_size
    flat = jnp.concatenate([shard.reshape(-1), jnp.zeros((pad,), shard.dtype)]) \
        if pad else shard.reshape(-1)
    q, scales = quantize_int8(flat, group_size)
    q_all = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    s_all = jax.lax.all_gather(scales, axis_name, axis=0, tiled=True)
    full = dequantize_int8(q_all, s_all, out_dtype, group_size)
    if pad:
        n = jax.lax.axis_size(axis_name)
        full = full.reshape(n, -1)[:, :shard.size].reshape(-1)
    return full


def all_to_all_quant_reduce(tensors: List[jnp.ndarray], groups_=None,
                            axis_name: str = "data", group_size: int = 256):
    """Reference-named entry (``:31``): hierarchical quantized gradient
    reduction over a tensor list. Returns per-tensor reduced shards."""
    flat, sizes = _flatten_concat(tensors)
    reduced = quantized_reduce_scatter(flat, axis_name, group_size)
    return reduced, sizes
