"""Sparse (row-wise) gradient allreduce for embedding tables.

Analog of the reference's sparse-gradient path
(``deepspeed/runtime/engine.py:2518-2587`` sparse_allreduce_bucket /
sparse_all_gather): for embedding-dominated models the dense (V, E) gradient
allreduce moves mostly zeros — each rank's gradient touches at most its own
batch's token rows. The reference all-gathers (indices, values) pairs of
torch sparse tensors; the TPU mapping keeps shapes STATIC: every rank
contributes exactly N = tokens-per-rank rows (duplicate token ids inside a
rank are pre-summed by the dense scatter-add of the lookup's vjp, so the
first occurrence carries the full row and repeats are zeroed), the (W, N)
ids + (W, N, E) rows ride one all-gather each over ICI, and a scatter-add
rebuilds the reduced dense gradient locally.

Comm volume: 2·(V·E) per rank for the dense ring vs (W-1)·N·(E+1) here —
the win is V / (W·N), e.g. 50k-vocab at 2k tokens/rank on 8 ranks ≈ 3x.

Correctness requires the table's gradient to be SPARSE by construction —
i.e. produced only by input lookups. Tied-embedding models get a dense
lm-head contribution in the same leaf and must keep the dense reduce (the
reference's torch sparse grads impose the same restriction: only
``sparse=True`` embedding layers produce sparse grads).
"""

import jax
import jax.numpy as jnp


def sparse_embedding_allreduce(grad, token_ids, axis_name: str = "data"):
    """Row-sparse allreduce inside a shard_map manual region.

    grad: (V, E) this rank's dense embedding gradient; token_ids: int array
    of this rank's batch token ids (any shape — flattened). Returns the
    (V, E) gradient summed across ``axis_name``, bit-equal in structure to a
    dense ``psum`` but exchanging only touched rows.
    """
    v, e = grad.shape
    flat = token_ids.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(flat)
    s = flat[order]
    # first occurrence of each id carries the (already locally-summed) row;
    # duplicates contribute zero so the cross-rank scatter-add never
    # double-counts
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    rows = grad[s] * first[:, None].astype(grad.dtype)
    all_ids = jax.lax.all_gather(s, axis_name)          # (W, N)
    all_rows = jax.lax.all_gather(rows, axis_name)      # (W, N, E)
    return jnp.zeros_like(grad).at[all_ids.reshape(-1)].add(
        all_rows.reshape(-1, e))
