"""Error-feedback compressed (1-bit) allreduce.

Analog of ``deepspeed/runtime/comm/compressed.py:13`` (CompressedBackend)
and ``runtime/comm/nccl.py:51`` (compressed_allreduce): signs + per-chunk
scale travel the wire; the residual between the true value and its
compression is fed back into the next round's input, preserving convergence
(1-bit Adam/LAMB's communication layer).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...utils import groups


def compressed_allreduce_body(x, worker_error, server_error,
                              axis_name: str = "data"):
    """Inside shard_map: 1-bit allreduce with worker AND server error
    feedback (reference keeps both buffers, ``runtime/comm/nccl.py:51``).

    Stage 1 (compress + exchange): each rank compresses (x + worker_error)
    to sign·scale; sign chunks + scales travel.
    Stage 2 (server): local dequant-sum of this rank's chunk, second
    compression with server_error feedback, allgather.
    Returns (allreduced approximation, new_worker_error, new_server_error).
    """
    n = jax.lax.axis_size(axis_name)
    corrected = x.astype(jnp.float32) + worker_error
    scale = jnp.mean(jnp.abs(corrected))
    signs = jnp.sign(corrected).astype(jnp.int8)
    new_worker_error = corrected - scale * signs.astype(jnp.float32)

    pad = (-signs.size) % n
    flat = signs.reshape(-1)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.int8)])
    chunks = flat.reshape(n, -1)
    sign_x = jax.lax.all_to_all(chunks, axis_name, split_axis=0, concat_axis=0, tiled=True)
    scale_all = jax.lax.all_gather(scale.reshape(1), axis_name, axis=0, tiled=True)  # (n,)
    contrib = sign_x.reshape(n, -1).astype(jnp.float32) * scale_all[:, None]
    reduced_chunk = jnp.sum(contrib, axis=0)                     # (chunk,)
    # second compression, with server error feedback on this rank's chunk
    corrected2 = reduced_chunk + server_error
    scale2 = jnp.mean(jnp.abs(corrected2))
    signs2 = jnp.sign(corrected2).astype(jnp.int8)
    new_server_error = corrected2 - scale2 * signs2.astype(jnp.float32)
    signs2_all = jax.lax.all_gather(signs2, axis_name, axis=0, tiled=True)
    scale2_all = jax.lax.all_gather(scale2.reshape(1), axis_name, axis=0, tiled=True)
    full = signs2_all.reshape(n, -1).astype(jnp.float32) * scale2_all[:, None]
    full = full.reshape(-1)
    if pad:
        full = full[:signs.size]
    return full.reshape(x.shape), new_worker_error, new_server_error


class CompressedBackend:
    """Eager facade (reference CompressedBackend): maintains per-buffer error
    feedback state and runs the compressed allreduce over the mesh.

    Single-controller convention: ``buffer`` carries per-rank contributions
    stacked on a leading dim of size n (sharded over the axis); the result is
    the same shape, every slot holding that rank's allreduced approximation.
    """

    def __init__(self, axis_name: str = "data"):
        self.axis_name = axis_name
        self._errors = {}

    def compressed_allreduce(self, buffer, key: str = "default"):
        mesh = groups.get_mesh()
        n = mesh.shape.get(self.axis_name, 1)
        if n <= 1:
            return buffer
        assert buffer.shape[0] == n, \
            f"leading dim {buffer.shape[0]} must equal axis size {n}"
        chunk = (buffer[0].size + n - 1) // n
        state = self._errors.get(key)
        if state is None or state[0].shape != buffer.shape:
            state = (jnp.zeros(buffer.shape, jnp.float32),
                     jnp.zeros((n, chunk), jnp.float32))
        w_err, s_err = state

        def body(x, we, se):
            out, new_we, new_se = compressed_allreduce_body(
                x[0], we[0], se[0], self.axis_name)
            return out[None], new_we[None], new_se[None]

        fn = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(self.axis_name), P(self.axis_name), P(self.axis_name)),
            out_specs=(P(self.axis_name), P(self.axis_name), P(self.axis_name)),
            axis_names={self.axis_name}, check_vma=True)
        out, new_we, new_se = fn(buffer, w_err, s_err)
        self._errors[key] = (new_we, new_se)
        return out
