"""Memory-mapped indexed dataset for token streams.

Analog of the reference's ``MMapIndexedDataset``
(``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py:369``):
variable-length integer sequences stored in a flat binary ``.bin`` file with
an ``.idx`` sidecar (dtype code + per-sample sizes + byte offsets +
document boundaries), read back zero-copy through ``numpy.memmap``. The
builder appends samples and finalizes the index; ``merge_file_`` splices
shard outputs (the reference's multi-worker pattern).

TPU-first notes: samples come back as numpy arrays (host-side); the
training engine stages whole microbatch bundles to device in one
``device_put`` (``runtime/engine.py _stage_leaf``), so the dataset layer
stays purely host/numpy and feeds any sampler. The format is
little-endian and versioned, but intentionally NOT byte-compatible with
the reference (no torch dependency, no legacy non-mmap variants).
"""

import os
import struct

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

# stable dtype codes (do not renumber)
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
           9: np.uint32}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def best_fitting_dtype(vocab_size=None):
    """Smallest unsigned int dtype that can hold token ids < vocab_size."""
    if vocab_size is not None and vocab_size < 65536:
        return np.uint16
    return np.int32


def index_file_path(prefix_path):
    return prefix_path + ".idx"


def data_file_path(prefix_path):
    return prefix_path + ".bin"


def dataset_exists(prefix_path):
    return (os.path.exists(index_file_path(prefix_path))
            and os.path.exists(data_file_path(prefix_path)))


class MMapIndexedDataset:
    """Zero-copy random access over a finalized builder output.

    ``ds[i]`` → 1-D numpy array (a view into the memmap); ``ds.get(i, offset,
    length)`` slices within a sample without materializing it. ``doc_idx``
    exposes document boundaries for samplers that pack documents.
    """

    def __init__(self, prefix_path):
        with open(index_file_path(prefix_path), "rb") as f:
            magic = f.read(8)
            if magic != _MAGIC:
                raise ValueError(f"{prefix_path}: not a DSTPU indexed dataset")
            version, code, n, n_docs = struct.unpack("<IIQQ", f.read(24))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self._dtype = np.dtype(_DTYPES[code])
            header = f.tell()
        self._sizes = np.memmap(index_file_path(prefix_path), np.int32,
                                "r", offset=header, shape=(n,))
        ptr_off = header + n * 4
        self._pointers = np.memmap(index_file_path(prefix_path), np.int64,
                                   "r", offset=ptr_off, shape=(n,))
        doc_off = ptr_off + n * 8
        self._doc_idx = np.memmap(index_file_path(prefix_path), np.int64,
                                  "r", offset=doc_off, shape=(n_docs,))
        self._data = np.memmap(data_file_path(prefix_path), self._dtype, "r")
        self._prefix = prefix_path

    def __len__(self):
        return len(self._sizes)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        ptr = self._pointers[idx]
        size = self._sizes[idx]
        return self._data[ptr:ptr + size]

    def get(self, idx, offset=0, length=None):
        ptr = self._pointers[idx] + offset
        size = self._sizes[idx] - offset
        if length is not None:
            size = min(size, length)
        return self._data[ptr:ptr + size]

    @property
    def sizes(self):
        return self._sizes

    @property
    def doc_idx(self):
        return self._doc_idx

    @property
    def dtype(self):
        return self._dtype

    def num_tokens(self, idx):
        return int(self._sizes[idx])

    def size(self, idx):
        return int(self._sizes[idx])

    @staticmethod
    def exists(prefix_path):
        return dataset_exists(prefix_path)


class MMapIndexedDatasetBuilder:
    """Append-only writer; ``finalize`` emits the ``.idx`` sidecar.

    Reference parity: ``add_item`` / ``end_document`` / ``merge_file_`` /
    ``finalize`` (``indexed_dataset.py:272`` and the MMap builder).
    """

    def __init__(self, out_prefix, dtype=np.int32):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        if self._dtype not in _CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(data_file_path(out_prefix), "wb")
        self._sizes = []
        self._doc_idx = [0]

    def add_item(self, tokens):
        arr = np.asarray(tokens, dtype=self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self):
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, other_prefix):
        """Append another finalized dataset (same dtype) in place."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self._dtype:
            raise ValueError("dtype mismatch in merge")
        base = len(self._sizes)
        self._sizes.extend(int(s) for s in other.sizes)
        # re-base the other's document boundaries onto this builder
        for d in np.asarray(other.doc_idx[1:]):
            self._doc_idx.append(base + int(d))
        with open(data_file_path(other_prefix), "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                self._bin.write(chunk)

    def finalize(self):
        self._bin.close()
        sizes = np.asarray(self._sizes, np.int32)
        pointers = np.zeros(len(sizes), np.int64)
        if len(sizes):
            np.cumsum(sizes[:-1], out=pointers[1:])  # element offsets
        if self._doc_idx[-1] != len(sizes):
            self._doc_idx.append(len(sizes))
        doc_idx = np.asarray(self._doc_idx, np.int64)
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<IIQQ", _VERSION, _CODES[self._dtype],
                                len(sizes), len(doc_idx)))
            f.write(sizes.tobytes())
            f.write(pointers.tobytes())
            f.write(doc_idx.tobytes())
        return MMapIndexedDataset(self._prefix)


def make_builder(out_prefix, impl="mmap", vocab_size=None, dtype=None):
    """Factory matching the reference's ``make_builder`` (``:60``)."""
    if impl != "mmap":
        raise ValueError("only the mmap implementation exists on TPU")
    return MMapIndexedDatasetBuilder(
        out_prefix, dtype or best_fitting_dtype(vocab_size))


def make_dataset(prefix_path, impl="mmap", skip_warmup=True):
    """Factory matching the reference's ``make_dataset`` (``:67``)."""
    if impl != "mmap":
        raise ValueError("only the mmap implementation exists on TPU")
    return MMapIndexedDataset(prefix_path)
