"""Distributed curriculum-aware data sampling.

Analog of ``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py:36``
(DeepSpeedDataSampler): deterministic epoch shuffling, per-dp-rank slicing,
optional curriculum (difficulty-filtered index pools).
"""

from typing import Iterator, Optional

import numpy as np


class DeepSpeedDataSampler:
    def __init__(self, total_samples: int, micro_batch_size: int,
                 data_parallel_rank: int = 0, data_parallel_size: int = 1,
                 gradient_accumulation_steps: int = 1, drop_last: bool = True,
                 shuffle: bool = True, seed: int = 0,
                 curriculum_scheduler=None, difficulty_of=None):
        self.total_samples = total_samples
        self.micro_batch_size = micro_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.gas = gradient_accumulation_steps
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.consumed_samples = 0
        self.curriculum = curriculum_scheduler
        self.difficulty_of = difficulty_of   # sample_idx -> difficulty metric
        self.global_batch_size = micro_batch_size * self.dp_size * self.gas

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.total_samples // self.global_batch_size * self.gas
        return (self.total_samples + self.global_batch_size - 1) // self.global_batch_size * self.gas

    def _indices(self):
        idx = np.arange(self.total_samples)
        if self.curriculum is not None and self.difficulty_of is not None:
            d = self.curriculum.get_current_difficulty()
            idx = idx[np.asarray([self.difficulty_of(int(i)) <= d for i in idx])]
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        return idx

    def __iter__(self) -> Iterator[np.ndarray]:
        idx = self._indices()
        n_batches = len(idx) // self.global_batch_size if self.drop_last else \
            (len(idx) + self.global_batch_size - 1) // self.global_batch_size
        for b in range(n_batches):
            chunk = idx[b * self.global_batch_size:(b + 1) * self.global_batch_size]
            # per-microbatch slices for this dp rank
            for g in range(self.gas):
                lo = g * self.micro_batch_size * self.dp_size + self.dp_rank * self.micro_batch_size
                mb = chunk[lo:lo + self.micro_batch_size]
                if len(mb) == 0:
                    continue
                self.consumed_samples += len(mb) * self.dp_size
                yield mb

    def state_dict(self):
        return {"epoch": self.epoch, "consumed_samples": self.consumed_samples,
                "seed": self.seed}

    def load_state_dict(self, sd):
        self.epoch = sd["epoch"]
        self.consumed_samples = sd["consumed_samples"]
        self.seed = sd.get("seed", self.seed)
