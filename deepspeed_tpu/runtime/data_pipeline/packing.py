"""Sequence packing: many short documents per training row.

Analog of the reference's packed-sample efficiency machinery (the
``DeepSpeedDataSampler``'s variable-batch regime and Megatron-style packed
pretraining data): short documents are first-fit packed into fixed-length
rows, and the batch carries everything the model needs to keep them
independent — ``segment_ids`` (masked in-kernel by the flash attention
kernels), per-document ``positions`` (RoPE/learned embeddings restart at
each document), and a ``loss_mask`` zeroing padding.

Padding uses segment id 0 (pads attend only pads; their loss is masked),
documents are 1-based.
"""

from typing import Dict, List, Sequence

import numpy as np


def pack_sequences(docs: Sequence[Sequence[int]], seq_len: int,
                   pad_id: int = 0) -> Dict[str, np.ndarray]:
    """First-fit pack token lists into (N, seq_len) rows.

    Returns dict(input_ids, labels, segment_ids, positions, loss_mask);
    ``labels == input_ids`` with padding masked via ``loss_mask`` (the
    engine's causal-LM loss convention). Documents longer than ``seq_len``
    are split into ``seq_len``-sized pieces (each piece becomes its own
    segment, matching the reference's sample-splitting behavior).
    """
    pieces: List[List[int]] = []
    for d in docs:
        d = list(d)
        if not d:
            continue
        for i in range(0, len(d), seq_len):
            pieces.append(d[i:i + seq_len])
    # first-fit decreasing: longest pieces first fill rows tighter
    pieces.sort(key=len, reverse=True)
    rows: List[List[List[int]]] = []
    space: List[int] = []
    for p in pieces:
        for r, free in enumerate(space):
            if len(p) <= free:
                rows[r].append(p)
                space[r] -= len(p)
                break
        else:
            rows.append([p])
            space.append(seq_len - len(p))

    n = len(rows)
    ids = np.full((n, seq_len), pad_id, np.int32)
    seg = np.zeros((n, seq_len), np.int32)
    pos = np.zeros((n, seq_len), np.int32)
    mask = np.zeros((n, seq_len), np.float32)
    for r, row in enumerate(rows):
        off = 0
        for s_idx, p in enumerate(row, start=1):
            ln = len(p)
            ids[r, off:off + ln] = p
            seg[r, off:off + ln] = s_idx
            pos[r, off:off + ln] = np.arange(ln)
            mask[r, off:off + ln] = 1.0
            off += ln
    return {"input_ids": ids, "labels": ids.copy(), "segment_ids": seg,
            "positions": pos, "loss_mask": mask}
