"""Random layerwise token dropping (random-LTD).

Analog of ``deepspeed/runtime/data_pipeline/data_routing/basic_layer.py:14``
(RandomLayerTokenDrop): during training, middle layers process a random
subset of tokens; the dropped tokens bypass the layer. On TPU the gather/
scatter are plain jnp ops (the reference's ``csrc/random_ltd`` kernels are
unnecessary — SURVEY §2.2 maps them to XLA gather/argsort).
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def random_token_select(rng, seq_len: int, keep: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sample ``keep`` sorted token indices out of ``seq_len``; returns
    (kept_idx (keep,), mask (seq_len,) bool)."""
    scores = jax.random.uniform(rng, (seq_len,))
    kept = jnp.sort(jnp.argsort(scores)[:keep])
    mask = jnp.zeros((seq_len,), bool).at[kept].set(True)
    return kept, mask


def gather_tokens(x, kept_idx):
    """x: (B, S, E) → (B, keep, E)."""
    return jnp.take(x, kept_idx, axis=1)


def scatter_tokens(full, processed, kept_idx):
    """Insert processed (B, keep, E) back into full (B, S, E) at kept_idx."""
    return full.at[:, kept_idx].set(processed)


class RandomLayerTokenDrop:
    """Wraps a layer fn: processes a random token subset, passes the rest
    through the residual stream."""

    def __init__(self, layer_fn, keep_ratio: float = 0.5):
        self.layer_fn = layer_fn
        self.keep_ratio = keep_ratio

    def __call__(self, params, x, rng, train: bool = True):
        if not train or self.keep_ratio >= 1.0:
            return self.layer_fn(params, x)
        s = x.shape[1]
        keep = max(1, int(s * self.keep_ratio))
        kept_idx, _ = random_token_select(rng, s, keep)
        sub = gather_tokens(x, kept_idx)
        sub_out = self.layer_fn(params, sub)
        return scatter_tokens(x, sub_out, kept_idx)


class RandomLTDScheduler:
    """Reserved-token ramp (reference data_routing/scheduler.py): the kept
    token count grows linearly from min to full over the schedule."""

    def __init__(self, total_layers: int, min_tokens: int, max_tokens: int,
                 schedule_steps: int):
        self.total_layers = total_layers
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.schedule_steps = schedule_steps

    def tokens_at(self, step: int) -> int:
        frac = min(1.0, step / max(1, self.schedule_steps))
        return int(self.min_tokens + frac * (self.max_tokens - self.min_tokens))
