"""Curriculum learning scheduler.

Analog of ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py``
(CurriculumScheduler): difficulty (e.g. sequence length) ramps with steps
under fixed_linear / fixed_root / fixed_discrete / custom schedules.
"""

import math
from typing import Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict):
        self.state = {}
        assert "curriculum_type" in config, "curriculum_type required"
        assert "min_difficulty" in config and "max_difficulty" in config
        self.state["min_difficulty"] = config["min_difficulty"]
        self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = config["min_difficulty"]
        # curriculum_type names the difficulty metric (e.g. "seqlen");
        # schedule_type picks the ramp. Configs predating the split used
        # curriculum_type for both, so fall back for compatibility.
        self.state["curriculum_type"] = config["curriculum_type"]
        stype = config.get("schedule_type", config["curriculum_type"])
        self.state["schedule_type"] = stype
        self.custom_get_difficulty: Optional[Callable] = None
        cfg = config.get("schedule_config", {})
        if stype in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in cfg and "difficulty_step" in cfg
            self.state["schedule"] = dict(cfg)
            if stype == FIXED_ROOT:
                self.state["schedule"].setdefault("root_degree", 2)
        elif stype == FIXED_DISCRETE:
            assert "difficulty" in cfg and "max_step" in cfg
            assert len(cfg["max_step"]) == len(cfg["difficulty"]) - 1
            self.state["schedule"] = dict(cfg)
        elif stype == CUSTOM:
            pass
        else:
            raise ValueError(f"unknown curriculum_type {stype}")

    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_custom_get_difficulty(self, fn: Callable):
        self.custom_get_difficulty = fn

    def update_difficulty(self, global_steps: int):
        s = self.state
        stype = s["schedule_type"]
        if stype == CUSTOM:
            assert self.custom_get_difficulty is not None
            d = self.custom_get_difficulty(global_steps)
        elif stype == FIXED_DISCRETE:
            cfg = s["schedule"]
            d = cfg["difficulty"][-1]
            for i, max_step in enumerate(cfg["max_step"]):
                if global_steps <= max_step:
                    d = cfg["difficulty"][i]
                    break
        else:
            cfg = s["schedule"]
            frac = min(1.0, global_steps / cfg["total_curriculum_step"])
            if stype == FIXED_ROOT:
                frac = frac ** (1.0 / cfg["root_degree"])
            d = s["min_difficulty"] + frac * (s["max_difficulty"] - s["min_difficulty"])
            step = cfg["difficulty_step"]
            d = int(d / step) * step
        s["current_difficulty"] = max(s["min_difficulty"],
                                      min(int(d), s["max_difficulty"]))
        return s["current_difficulty"]

    def state_dict(self):
        return dict(self.state)

    def load_state_dict(self, sd):
        self.state.update(sd)
