"""Offline dataset analysis for curriculum learning.

Analog of the reference's ``DataAnalyzer`` / ``DistributedDataAnalyzer``
(``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py:22,455``):
map user metric functions over every sample of a dataset, persist the
results as indexed datasets, and produce the inverse (metric value →
samples) and percentile indexes that ``DeepSpeedDataSampler`` consumes as a
curriculum difficulty source.

Host-side by design (data prep never touches the accelerator). The
reference fans out over torch dataloader workers + threads and merges
per-worker files; here workers are a thread pool over contiguous sample
ranges (map is numpy/user-code bound, and the merge path is identical),
and ``DistributedDataAnalyzer`` keeps the per-worker-shard file layout so
multi-host runs can split by rank and merge with ``merge_file_``.

Outputs under ``save_path`` per metric (reference file-name parity):
  <metric>_sample_to_metric      indexed dataset: value of each sample
  <metric>_index_to_sample       indexed dataset: samples per sorted value
  <metric>_index_to_metric       indexed dataset: the sorted unique values
  <metric>_metric_value_max/min  scalar .npy
"""

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence

import numpy as np

from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder,
                              dataset_exists)

SINGLE_VALUE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"


def _metric_path(save_path, metric_name, suffix):
    return os.path.join(save_path, f"{metric_name}_{suffix}")


class DataAnalyzer:
    """Map/reduce metric analysis over an indexable dataset.

    ``metric_functions`` take a batch (list of samples) and return one value
    per sample (``single_value_per_sample``) or a partial aggregate to be
    summed (``accumulate_value_over_samples``), mirroring the reference's
    two metric types (``data_analyzer.py:89``).
    """

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable],
                 metric_types: Optional[Sequence[str]] = None,
                 save_path: str = "./data_analysis",
                 num_workers: int = 1, batch_size: int = 1024,
                 metric_dtypes: Optional[Sequence] = None):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types or [SINGLE_VALUE] * len(self.metric_names))
        self.metric_dtypes = list(metric_dtypes or [np.int64] * len(self.metric_names))
        self.save_path = save_path
        self.num_workers = max(1, num_workers)
        self.batch_size = batch_size
        os.makedirs(save_path, exist_ok=True)

    # ---- map ----

    def _map_range(self, worker_id: int, lo: int, hi: int):
        """Compute every metric over samples [lo, hi); returns per-metric
        numpy arrays (single-value) or partial aggregates (accumulate)."""
        out = []
        for mt in self.metric_types:
            out.append([] if mt == SINGLE_VALUE else None)
        for start in range(lo, hi, self.batch_size):
            batch = [self.dataset[i] for i in range(start, min(start + self.batch_size, hi))]
            for k, (fn, mt) in enumerate(zip(self.metric_functions, self.metric_types)):
                res = fn(batch)
                if mt == SINGLE_VALUE:
                    out[k].append(np.asarray(res))
                else:
                    out[k] = res if out[k] is None else out[k] + res
        for k, mt in enumerate(self.metric_types):
            if mt == SINGLE_VALUE:
                out[k] = (np.concatenate(out[k]) if out[k]
                          else np.zeros((0,), self.metric_dtypes[k]))
        return out

    def run_map(self):
        """Parallel map over worker ranges → per-worker in-memory results."""
        n = len(self.dataset)
        bounds = np.linspace(0, n, self.num_workers + 1).astype(int)
        ranges = [(w, bounds[w], bounds[w + 1]) for w in range(self.num_workers)]
        if self.num_workers == 1:
            return [self._map_range(*ranges[0])]
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            return list(pool.map(lambda r: self._map_range(*r), ranges))

    # ---- reduce ----

    def run_reduce(self, worker_results):
        """Merge worker shards and write the index files (reference
        ``merge_map_results``, ``data_analyzer.py:279``)."""
        for k, (name, mt, dt) in enumerate(zip(self.metric_names,
                                               self.metric_types,
                                               self.metric_dtypes)):
            if mt == ACCUMULATE:
                total = None
                for wr in worker_results:
                    total = wr[k] if total is None else total + wr[k]
                b = MMapIndexedDatasetBuilder(
                    _metric_path(self.save_path, name, "accumulated"), dt)
                b.add_item(np.asarray(total).reshape(-1))
                b.finalize()
                continue
            values = np.concatenate([wr[k] for wr in worker_results]).astype(dt)
            # sample -> metric
            b = MMapIndexedDatasetBuilder(
                _metric_path(self.save_path, name, "sample_to_metric"), dt)
            for v in values:
                b.add_item([v])
            b.finalize()
            # metric -> samples, ordered by value (curriculum consumption)
            order = np.argsort(values, kind="stable")
            uniq, starts = np.unique(values[order], return_index=True)
            i2s = MMapIndexedDatasetBuilder(
                _metric_path(self.save_path, name, "index_to_sample"), np.int64)
            i2m = MMapIndexedDatasetBuilder(
                _metric_path(self.save_path, name, "index_to_metric"), dt)
            bounds = list(starts) + [len(order)]
            for u, lo, hi in zip(uniq, bounds[:-1], bounds[1:]):
                i2s.add_item(order[lo:hi])
                i2m.add_item([u])
            i2s.finalize()
            i2m.finalize()
            np.save(_metric_path(self.save_path, name, "metric_value_max.npy"),
                    values.max() if len(values) else 0)
            np.save(_metric_path(self.save_path, name, "metric_value_min.npy"),
                    values.min() if len(values) else 0)

    def run_map_reduce(self):
        self.run_reduce(self.run_map())
        return self.save_path


class DistributedDataAnalyzer(DataAnalyzer):
    """Rank-sharded variant (reference ``data_analyzer.py:455``): each rank
    maps its contiguous slice and writes a shard dataset; rank 0 merges the
    shards with ``merge_file_`` before reducing. On a multi-host TPU pod
    each host runs with its (rank, world_size); in-process tests drive all
    ranks sequentially."""

    def __init__(self, *args, rank: int = 0, world_size: int = 1, **kw):
        super().__init__(*args, **kw)
        self.rank = rank
        self.world_size = max(1, world_size)

    def _shard_prefix(self, name, rank):
        return _metric_path(self.save_path, name, f"shard{rank}")

    def run_map(self):
        n = len(self.dataset)
        bounds = np.linspace(0, n, self.world_size + 1).astype(int)
        lo, hi = bounds[self.rank], bounds[self.rank + 1]
        results = self._map_range(self.rank, lo, hi)
        for k, (name, mt, dt) in enumerate(zip(self.metric_names,
                                               self.metric_types,
                                               self.metric_dtypes)):
            if mt == SINGLE_VALUE:
                b = MMapIndexedDatasetBuilder(self._shard_prefix(name, self.rank), dt)
                b.add_item(np.asarray(results[k]).reshape(-1))
                b.finalize()
            else:   # accumulate partials persist too, so rank 0 can sum them
                np.save(self._shard_prefix(name, self.rank) + "_acc.npy",
                        np.asarray(results[k]))
        return results

    def run_map_reduce(self):
        self.run_map()
        if self.rank != 0:
            return None
        merged = []
        for k, (name, mt, dt) in enumerate(zip(self.metric_names,
                                               self.metric_types,
                                               self.metric_dtypes)):
            if mt != SINGLE_VALUE:
                total = None
                for r in range(self.world_size):
                    path = self._shard_prefix(name, r) + "_acc.npy"
                    if not os.path.exists(path):
                        raise FileNotFoundError(
                            f"accumulate shard {r} for metric {name} missing — "
                            f"did every rank run run_map()?")
                    part = np.load(path)
                    total = part if total is None else total + part
                merged.append(total)
                continue
            parts = []
            for r in range(self.world_size):
                prefix = self._shard_prefix(name, r)
                if not dataset_exists(prefix):
                    raise FileNotFoundError(
                        f"shard {r} for metric {name} missing — did every rank run run_map()?")
                parts.append(np.asarray(MMapIndexedDataset(prefix)[0]))
            merged.append(np.concatenate(parts))
        self.run_reduce([merged])
        return self.save_path


def curriculum_difficulty_fn(save_path: str, metric_name: str) -> Callable[[int], float]:
    """``difficulty_of`` callable for ``DeepSpeedDataSampler`` backed by a
    finished analysis (the reference wires the same files into
    ``DeepSpeedDataSampler`` via ``curriculum_learning`` config)."""
    ds = MMapIndexedDataset(_metric_path(save_path, metric_name, "sample_to_metric"))
    return lambda i: float(ds[i][0])
