from .packing import pack_sequences  # noqa: F401
