"""Latency-oriented tensor-parallel collectives for per-step decode.

Tensor-parallel decode (DeepSpeed-Inference, arXiv 2207.00032) spends a
growing share of each step in the two per-layer all-reduces (attention
output, MLP output) plus the vocab-sharded logit all-gather: at decode batch
sizes the matmuls are bandwidth-bound and short, so the collectives stop
hiding behind compute. This module is the serving-side collective layer the
``shard_map``-compiled frame loops call inside the manual region — three
interchangeable lowerings per collective, picked by ``TPCollectives`` flags:

- **exact** — ``lax.psum`` / ``lax.all_gather``. Bit-deterministic and the
  default: the tp=1 vs tp=N greedy token-parity tests pin this path.
- **overlap** (T3, arXiv 2401.16677) — the all-reduce decomposed into a
  ring reduce-scatter + ring all-gather of ``degree`` chunks via
  ``lax.ppermute``. One monolithic ``psum`` is an opaque scheduling unit;
  2*(degree-1) small ppermute steps give XLA's latency-hiding scheduler
  the freedom to interleave each hop with whatever neighboring compute is
  independent of the not-yet-arrived chunks — the fusion T3 adds in
  hardware, approximated at the scheduling level. Summation order differs
  from ``psum`` (ring order), so this path is parity-at-tolerance, not
  bit-exact.
- **quantized** (EQuARX, arXiv 2506.17615) — low-precision payloads with
  per-row f32 scales for the activation all-reduces, the masked embedding
  psum, and the logit all-gather: 2-4x less inter-chip traffic per step in
  exchange for bounded error. Two wire formats, picked by
  ``TPCollectives.payload``: ``"int8"`` (default, symmetric round-to-
  nearest, amax/127) and ``"fp8"`` (e4m3 per Big-Send-off-style scaled
  casts, amax/448 — same byte width as int8 but a wider dynamic range
  within each scaled row, trading one mantissa bit of uniform precision
  for graceful handling of heavy-tailed activations).
  The all-reduce is a quantized reduce-scatter (``all_to_all`` of int8
  chunks + scales, dequantize-accumulate locally) followed by an int8
  all-gather of the reduced chunks — wire bytes 2(N-1)/N x 1 byte per
  element, a true 4x under the exact f32 ring, which graft-cost rule
  GL202 proves statically per traced program (the earlier gather-based
  lowering moved (N-1) x 1 byte per element: int8 on the wire but ZERO
  saving over an exact ring all-reduce at N=8 — exactly the kind of
  claim-vs-program gap the cost model exists to catch). Tolerance
  contract: symmetric per-chunk-row quantization bounds the element
  error by ``amax_row / 127`` per participating shard plus one
  requantization of the reduced chunk (the parity test in
  ``tests/test_serving_tp.py`` asserts final logits within rtol=0.1 of
  the exact path and that generation still completes).

All functions must be called inside a ``shard_map`` manual region where
``axis`` is a manual mesh axis; ``degree == 1`` short-circuits to identity.
"""

import dataclasses

import jax
import jax.numpy as jnp


def psum_exact(x, axis: str):
    return jax.lax.psum(x, axis)


def all_gather_exact(x, axis: str, gather_axis: int = -1):
    return jax.lax.all_gather(x, axis, axis=gather_axis, tiled=True)


# ---------------------------------------------------------------------------
# overlap path: ring all-reduce as ppermute chunks (T3-style)
# ---------------------------------------------------------------------------


def psum_ring(x, axis: str, degree: int):
    """All-reduce as ring reduce-scatter + ring all-gather over ``degree``
    chunks of the last dim, each hop an independent ``ppermute`` XLA can
    schedule around neighboring compute. Falls back to ``psum`` when the
    last dim doesn't split evenly (tiny tensors aren't worth chunking)."""
    d = x.shape[-1]
    if degree == 1:
        return x
    if d % degree != 0:
        return jax.lax.psum(x, axis)
    r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % degree) for i in range(degree)]
    chunks = x.reshape(x.shape[:-1] + (degree, d // degree))

    def chunk(i):
        # traced chunk index (depends on the shard's ring position)
        return jax.lax.dynamic_index_in_dim(chunks, i % degree, axis=-2,
                                            keepdims=False)

    # reduce-scatter: the partial for chunk j starts at shard j+1 and
    # accumulates one local contribution per hop, landing fully reduced on
    # shard j after degree-1 hops — so shard r seeds chunk r-1 and adds the
    # chunk matching each received partial (received index decreases by one
    # per hop)
    acc = chunk(r + degree - 1)
    for k in range(1, degree):
        acc = jax.lax.ppermute(acc, axis, perm) + chunk(r + 2 * degree - 1 - k)
    # all-gather the reduced chunks back around the ring
    parts = [acc]
    for _ in range(degree - 1):
        parts.append(jax.lax.ppermute(parts[-1], axis, perm))
    # shard r produced chunk r and received chunk (r-1), (r-2), ... in turn;
    # scatter them back to their chunk slots position-independently
    out = jnp.zeros_like(chunks)
    for k, p in enumerate(parts):
        out = jax.lax.dynamic_update_index_in_dim(
            out, p[..., None, :], (r - k) % degree, axis=-2)
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# quantized path: int8/fp8 payloads + per-row f32 scales (EQuARX-style)
# ---------------------------------------------------------------------------

_FP8_MAX = 448.0  # float8_e4m3fn largest finite value


def _quantize_int8(x):
    """Symmetric per-row (last-dim) int8 quantization. Returns (q, scale)
    with ``x ~= q * scale``; all-zero rows get scale 0 (q is 0 too, so the
    dequantized product stays exactly 0 instead of NaN)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = amax / 127.0
    q = jnp.where(scale > 0, jnp.round(x.astype(jnp.float32)
                                       / jnp.where(scale > 0, scale, 1.0)), 0)
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _quantize_fp8(x):
    """Per-row scaled cast to e4m3: same one byte per element on the wire
    as int8, but the scaled row spans e4m3's full dynamic range instead of
    a uniform grid. The clip pins the row amax to the largest finite e4m3
    value so the cast can never produce inf/NaN."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = amax / _FP8_MAX
    q = jnp.where(scale > 0, x.astype(jnp.float32)
                  / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.clip(q, -_FP8_MAX, _FP8_MAX).astype(jnp.float8_e4m3fn)
    return q, scale


def _quantize(x, payload: str):
    """Dispatch on the wire format. ``payload`` is a static string baked
    into the traced program — GL202 sees either int8 or float8_e4m3fn
    collective operands, never both."""
    if payload == "fp8":
        return _quantize_fp8(x)
    return _quantize_int8(x)


def psum_quantized(x, axis: str, degree: int, payload: str = "int8"):
    """All-reduce with one-byte payloads, reduce-scatter shaped so the wire
    bytes actually shrink: chunk the last dim ``degree`` ways, quantize
    each chunk with its own per-row scale, ``all_to_all`` the quantized
    chunks (shard r receives every shard's chunk r — (N-1)/N x 1
    byte/element), dequantize-accumulate locally in f32, then requantize
    the reduced chunk once and all-gather it back around ((N-1)/N x 1
    byte/element again). Total quantized wire: 2(N-1)/N bytes per element
    — the same ring schedule as an exact all-reduce at a quarter the
    width, which is the EQuARX claim graft-cost GL202 checks against the
    exact program. ``payload`` picks int8 or fp8-e4m3 chunks (see
    ``_quantize``); both are one byte on the wire.

    Error: each contribution is quantized once (finer per-chunk scales
    than whole-row) plus one requantization of the reduced chunk.

    Falls back to a gather-based quantized exchange when the last dim
    doesn't chunk evenly (tiny tensors aren't worth scattering)."""
    if degree == 1:
        return x
    d = x.shape[-1]
    if d % degree != 0:
        q, s = _quantize(x, payload)
        qg = jax.lax.all_gather(q, axis)               # (degree, ...)
        sg = jax.lax.all_gather(s, axis)
        return jnp.sum(qg.astype(jnp.float32) * sg, axis=0).astype(x.dtype)
    shard = d // degree
    chunks = x.reshape(x.shape[:-1] + (degree, shard))
    q, s = _quantize(chunks, payload)                  # s: (..., degree, 1)
    ca = x.ndim - 1                                    # the chunk axis
    qx = jax.lax.all_to_all(q, axis, split_axis=ca, concat_axis=ca,
                            tiled=True)
    sx = jax.lax.all_to_all(s, axis, split_axis=ca, concat_axis=ca,
                            tiled=True)
    red = jnp.sum(qx.astype(jnp.float32) * sx, axis=-2)   # (..., shard)
    q2, s2 = _quantize(red, payload)
    qg = jax.lax.all_gather(q2, axis, axis=x.ndim - 1, tiled=True)
    sg = jax.lax.all_gather(s2, axis, axis=x.ndim - 1, tiled=True)
    deq = (qg.reshape(qg.shape[:-1] + (degree, shard)).astype(jnp.float32)
           * sg[..., None])
    return deq.reshape(x.shape[:-1] + (d,)).astype(x.dtype)


def all_gather_quantized(x, axis: str, degree: int, payload: str = "int8"):
    """Tiled all-gather of the LAST dim with one-byte payloads (the
    per-step logit exchange of a vocab-sharded LM head)."""
    if degree == 1:
        return x
    q, s = _quantize(x, payload)                       # s: (..., 1)
    qg = jax.lax.all_gather(q, axis, axis=q.ndim - 1, tiled=True)
    sg = jax.lax.all_gather(s, axis, axis=s.ndim - 1, tiled=True)  # (..., tp)
    shard = x.shape[-1]
    deq = (qg.reshape(qg.shape[:-1] + (degree, shard)).astype(jnp.float32)
           * sg[..., None])
    return deq.reshape(qg.shape).astype(x.dtype)


# ---------------------------------------------------------------------------
# the layer the frame loops call
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TPCollectives:
    """Per-engine choice of collective lowerings (see module docstring).

    ``quantized`` switches the activation all-reduces, the masked
    embedding psum, AND the logit all-gather to quantized payloads;
    ``payload`` picks the wire format ("int8" default, "fp8" = e4m3);
    ``overlap`` switches the MLP all-reduce (the one with downstream-
    independent compute to hide behind, per T3) to the chunked ring.
    ``quantized`` wins when both are set — the quantized exchange is
    already chunk-shaped."""

    axis: str
    degree: int
    quantized: bool = False
    overlap: bool = False
    payload: str = "int8"

    def psum_attn(self, x):
        """Attention-output (row-parallel wo) all-reduce."""
        if self.degree == 1:
            return x
        if self.quantized:
            return psum_quantized(x, self.axis, self.degree, self.payload)
        return psum_exact(x, self.axis)

    def psum_mlp(self, x):
        """MLP-output (row-parallel w_out) all-reduce — the overlap target."""
        if self.degree == 1:
            return x
        if self.quantized:
            return psum_quantized(x, self.axis, self.degree, self.payload)
        if self.overlap:
            return psum_ring(x, self.axis, self.degree)
        return psum_exact(x, self.axis)

    def psum_embed(self, x):
        """Vocab-sharded embedding-lookup reduce. Each token row is nonzero
        on exactly one shard, so exact mode's psum is a select; under
        ``quantized`` the rows ride the same one-byte exchange as the
        activation all-reduces — the all-zero rows of non-owning shards
        quantize to scale 0 and contribute exactly 0, so the only error is
        one quantize/dequantize of the owning shard's real row, and the
        per-step embedding traffic drops with everything else."""
        if self.degree == 1:
            return x
        if self.quantized:
            return psum_quantized(x, self.axis, self.degree, self.payload)
        return psum_exact(x, self.axis)

    def gather_logits(self, x):
        """Vocab-sharded logits (…, V/tp) -> (…, V)."""
        if self.degree == 1:
            return x
        if self.quantized:
            return all_gather_quantized(x, self.axis, self.degree,
                                        self.payload)
        return all_gather_exact(x, self.axis, gather_axis=-1)
