"""Logical-axis sharding rules: the TPU-native core of ZeRO and TP.

The reference implements ZeRO by hand-partitioning flat fp32 buffers and
scheduling NCCL collectives (``runtime/zero/stage_1_and_2.py:646`` round-robin
partitioning, ``stage3.py:1282`` reduce-scatter pump). On TPU the same
semantics are expressed declaratively: every parameter carries a tuple of
*logical* axis names; rules map logical axes to mesh axes; XLA's SPMD
partitioner then emits the exact allgather/reduce-scatter schedule the
reference hand-codes:

- ZeRO-0: params/grads/optimizer replicated over ``data``; grads all-reduced.
- ZeRO-1: optimizer state (master weights, moments) additionally sharded over
  the ZeRO axes — the update runs shard-local, then updated params are
  all-gathered (same schedule as ``stage_1_and_2.py`` partition + allgather).
- ZeRO-2: gradients annotated with the optimizer-state sharding, so XLA
  lowers the grad reduction to reduce-scatter instead of all-reduce.
- ZeRO-3: parameters themselves stored sharded; the forward/backward
  allgathers are compiled into the step (prefetching is XLA's latency-hiding
  scheduler doing what ``partitioned_param_coordinator.py`` does by hand).

Tensor parallelism (Megatron-style column/row splits, reference
``module_inject/auto_tp.py``) is the same mechanism: "heads"/"mlp"/"vocab"
logical axes map to the ``tensor`` mesh axis.
"""

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import groups

# Logical axis vocabulary used by deepspeed_tpu.models.
#   batch      – per-example batch dim of activations
#   seq_act    – sequence dim of activations (sharded under sequence parallelism)
#   vocab      – vocabulary dim of embedding / lm head
#   embed      – model (hidden) dim
#   heads      – attention query-head dim
#   kv_heads   – attention kv-head dim (GQA)
#   head_dim   – per-head feature dim
#   mlp        – MLP intermediate dim
#   expert     – expert dim of MoE weights
#   layers     – stacked-layer (scan) dim
#   unmodeled  – never sharded

# (logical_axis, mesh_axis) rules; first match wins. A mesh axis is consumed
# at most once per parameter (XLA requirement).
BASE_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("batch", ("zrep", "data", "expert")),
    ("seq_act", "seq"),
    ("vocab", "tensor"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("mlp", "tensor"),
    ("expert", "expert"),
    ("embed", None),
    ("head_dim", None),
    ("layers", "pipe"),   # stage-sharded layer stack when pipeline parallel
    ("unmodeled", None),
)

# ZeRO param/optimizer-state sharding: shard the "embed" logical axis over the
# ZeRO axes (data×expert×seq product). Norm/bias vectors (1D "embed") stay
# replicated — sharding tiny vectors wastes collectives, mirroring the
# reference's round-robin which also keeps small tensors whole
# (stage_1_and_2.py:646 partitions the *flat* buffer; here sharding is
# per-tensor so we skip sub-threshold tensors instead).
FSDP_AXIS = ("data", "expert", "seq")


def zero_rules(stage: int, base=BASE_RULES):
    """Rules for *parameter* sharding at a given ZeRO stage."""
    if stage >= 3:
        return tuple(("embed", FSDP_AXIS) if r[0] == "embed" else r for r in base)
    return base


def optimizer_state_rules(stage: int, base=BASE_RULES, hpz: bool = False):
    """Rules for optimizer-state (master weights/moments) sharding.

    With ``hpz`` (ZeRO++ hierarchical partitioning, reference
    ``groups.py:529`` + ``partition_parameters.py:1653``), optimizer state
    shards over the FULL data-parallel world (zrep × data), while params keep
    the within-group secondary partition — the post-step param refresh is a
    zrep-axis allgather XLA emits from the sharding mismatch."""
    if stage >= 1:
        axes = (("zrep",) + FSDP_AXIS) if hpz else FSDP_AXIS
        return tuple(("embed", axes) if r[0] == "embed" else r for r in base)
    return base


def _first_shardable(logical_axes, mesh, used):
    """Pick the first logical axis to receive the FSDP axes (largest-dim heuristic
    is unnecessary: 'embed' appears in every weight matrix)."""
    return None


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules=BASE_RULES,
                    mesh: Optional[Mesh] = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    Skips assignments whose mesh axis was already consumed by an earlier dim,
    and drops sharding when the dim size is unknown (callers with shapes should
    use :func:`shard_spec_for`).
    """
    if mesh is None:
        mesh = groups.get_mesh()
    rule_map = {name: ax for name, ax in rules}
    used = set()
    out = []
    for ax in logical_axes:
        mesh_axes = rule_map.get(ax) if ax is not None else None
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        free = tuple(m for m in mesh_axes if m not in used and mesh.shape.get(m, 1) > 1)
        if not free:
            out.append(None)
            continue
        used.update(free)
        out.append(free if len(free) > 1 else free[0])
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def shard_spec_for(shape: Sequence[int],
                   logical_axes: Sequence[Optional[str]],
                   rules=BASE_RULES,
                   mesh: Optional[Mesh] = None,
                   min_shard_size: int = 2 ** 11) -> P:
    """PartitionSpec for a concrete shape: validates divisibility, skips
    sub-threshold tensors (small vectors stay replicated)."""
    if mesh is None:
        mesh = groups.get_mesh()
    total = 1
    for s in shape:
        total *= int(s)
    if total < min_shard_size:
        return P()
    spec = logical_to_spec(logical_axes, rules, mesh)
    out = []
    for i, part in enumerate(spec):
        if part is None:
            out.append(None)
            continue
        axes = (part,) if isinstance(part, str) else part
        import math
        n = math.prod(mesh.shape[a] for a in axes)
        if shape[i] % n != 0:
            out.append(None)  # not divisible → replicate this dim
        else:
            out.append(part)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def tree_shardings(abstract_params, logical_tree, rules=BASE_RULES, mesh=None):
    """Build a pytree of NamedShardings matching ``abstract_params``.

    ``logical_tree`` mirrors the param tree; each leaf is a tuple of logical
    axis names (len == ndim of the corresponding param).
    """
    if mesh is None:
        mesh = groups.get_mesh()

    def one(p, axes):
        spec = shard_spec_for(p.shape, axes, rules, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree.map(one, abstract_params, logical_tree,
                        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x))


def tree_specs(abstract_params, logical_tree, rules=BASE_RULES, mesh=None,
               min_shard_size: int = 2 ** 11):
    """Like :func:`tree_shardings` but returns raw PartitionSpecs."""
    if mesh is None:
        mesh = groups.get_mesh()

    def one(p, axes):
        return shard_spec_for(p.shape, axes, rules, mesh,
                              min_shard_size=min_shard_size)

    return jax.tree.map(one, abstract_params, logical_tree,
                        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x))


def inference_tp_specs(abstract_params, logical_tree, mesh: Mesh,
                       axis: str = "tp", vocab_sharded: bool = True,
                       rules=BASE_RULES):
    """PartitionSpec tree for tensor-parallel SERVING over a 1-D mesh.

    Same logical-axis vocabulary and rule set as training (BASE_RULES), with
    the ``tensor`` mesh axis rebound to the serving mesh's ``axis`` — the
    Megatron column/row layout falls out of the rules: heads/kv_heads/mlp
    column-sharded, wo/w_out row-sharded (their contraction dim carries the
    same logical axis), vocab-sharded embedding + LM head.

    Differences from the training spec builders, both deliberate:

    - NO min-size threshold. The ``shard_map``-compiled frame loops issue
      manual per-layer collectives whose arithmetic assumes every heads/
      kv_heads/mlp-carrying tensor is actually sharded — a silently
      replicated wq would double-count in the attention-output psum. The
      caller validates divisibility up front
      (``model_implementations.archs.validate_tp_serving``) instead of
      falling back per-tensor.
    - ``vocab_sharded=False`` drops the vocab rule entirely (embedding and
      LM head replicated, no logit all-gather) — the fallback for vocab
      sizes the tp degree doesn't divide, which only costs memory, never
      correctness.
    """
    eff = []
    for la, ma in rules:
        if ma == "tensor":
            ma = axis
        elif isinstance(ma, tuple):
            ma = tuple(axis if m == "tensor" else m for m in ma)
        if la == "vocab" and not vocab_sharded:
            ma = None
        eff.append((la, ma))
    return tree_specs(abstract_params, logical_tree, rules=tuple(eff),
                      mesh=mesh, min_shard_size=0)


def batch_spec(mesh=None) -> P:
    """Sharding of a (batch, seq, ...) activation batch: batch over data-like
    axes, sequence over the seq axis."""
    if mesh is None:
        mesh = groups.get_mesh()
    batch_axes = tuple(a for a in groups.BATCH_AXES if mesh.shape.get(a, 1) > 1)
    seq_axis = "seq" if mesh.shape.get("seq", 1) > 1 else None
    return P(batch_axes if batch_axes else None, seq_axis)


def constrain(x, spec: P, mesh=None):
    """with_sharding_constraint helper usable inside jit."""
    if mesh is None:
        mesh = groups.get_mesh()
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def current_manual_axes():
    """Mesh axes currently in shard_map manual mode at this trace point.

    Sharding constraints must not mention manual axes; layout anchors filter
    through this so model code works both under plain SPMD jit and inside
    partial-auto shard_map regions (e.g. the ZeRO++ quantized-collective
    step)."""
    try:
        am = jax.sharding.get_abstract_mesh()
        return set(getattr(am, "manual_axes", ()) or ())
    except Exception:
        return set()


