"""Fused optimizers.

TPU-native analog of the reference's native optimizer zoo
(``csrc/adam/multi_tensor_adam.cu`` FusedAdam, ``csrc/adam/cpu_adam.cpp``
DeepSpeedCPUAdam, ``csrc/lamb``, ``csrc/lion``, ``csrc/adagrad``). The
reference fuses updates with hand-rolled multi-tensor CUDA kernels; under XLA
a whole-pytree ``tree_map`` update inside the jitted step compiles to the same
fused elementwise kernels, sharded to match the optimizer-state layout (which
is how ZeRO-1 shard-local updates fall out for free).

Protocol (functional):
    opt = FusedAdam(lr=..., ...)
    state = opt.init(params)                  # moments allocated like params
    new_params, new_state = opt.apply(grads, state, params, lr=lr)

Update math runs in fp32 regardless of grad/param dtype.
"""

import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


def _f32(t):
    return jax.tree.map(lambda x: x.astype(jnp.float32), t)


class Optimizer:
    """Base: subclasses define _init_slot(p) and _update_one(g, p, slots, ctx).

    When ``master_weights`` is True (set by the engine for bf16/fp16
    training), each low-precision param carries an fp32 master copy in its
    slot dict (reference ``runtime/bf16_optimizer.py:34``): the update reads
    and writes the master, and the low-precision param is derived by cast —
    small updates are never lost to the low-precision round-trip.
    """

    name = "base"
    defaults: Dict[str, Any] = {}
    master_weights = False

    def __init__(self, **hyper):
        unknown = set(hyper) - set(self.defaults)
        if unknown:
            raise TypeError(f"{type(self).__name__} got unknown hyperparameters {sorted(unknown)}")
        self.hyper = {**self.defaults, **hyper}

    def _needs_master(self, p):
        return self.master_weights and p.dtype != jnp.float32

    def init(self, params):
        flat_p, treedef = jax.tree.flatten(params)
        slots = []
        for p in flat_p:
            s = self._init_slot(p)
            if self._needs_master(p):
                s = dict(s)
                s["master"] = p.astype(jnp.float32)
            slots.append(s)
        return {"step": jnp.zeros((), jnp.int32),
                "slots": jax.tree.unflatten(treedef, slots)}

    def apply(self, grads, state, params, lr: Optional[jnp.ndarray] = None):
        step = state["step"] + 1
        ctx = dict(self.hyper)
        if lr is not None:
            ctx["lr"] = lr
        ctx["step"] = step.astype(jnp.float32)

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state["slots"])
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            p_eff = s["master"] if "master" in s else p
            np_, ns_ = self._update_one(g.astype(jnp.float32), p_eff, s, ctx)
            if "master" in s:
                ns_ = dict(ns_)
                ns_["master"] = np_
            new_p.append(np_.astype(p.dtype))
            new_s.append(ns_)
        return (jax.tree.unflatten(treedef, new_p),
                {"step": step, "slots": jax.tree.unflatten(treedef, new_s)})

    def _init_slot(self, p):
        raise NotImplementedError

    def _update_one(self, g, p, slots, ctx):
        raise NotImplementedError


class FusedAdam(Optimizer):
    """Adam/AdamW. Analog of reference FusedAdam (``csrc/adam``) — under jit
    the whole update is one fused elementwise kernel per dtype/shape bucket."""

    name = "adam"
    defaults = dict(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                    adam_w_mode=True, bias_correction=True, amsgrad=False)

    def _init_slot(self, p):
        z = jnp.zeros(p.shape, jnp.float32)
        slot = {"m": z, "v": z}
        if self.hyper["amsgrad"]:
            slot["vmax"] = z
        return slot

    def _update_one(self, g, p, slots, ctx):
        b1, b2 = ctx["betas"]
        p32 = p.astype(jnp.float32)
        if ctx["weight_decay"] != 0.0 and not ctx["adam_w_mode"]:
            g = g + ctx["weight_decay"] * p32
        m = b1 * slots["m"] + (1 - b1) * g
        v = b2 * slots["v"] + (1 - b2) * jnp.square(g)
        if ctx["bias_correction"]:
            mh = m / (1 - jnp.power(b1, ctx["step"]))
            vh = v / (1 - jnp.power(b2, ctx["step"]))
        else:
            mh, vh = m, v
        new_slots = {"m": m, "v": v}
        if self.hyper["amsgrad"]:
            vmax = jnp.maximum(slots["vmax"], vh)
            new_slots["vmax"] = vmax
            vh = vmax
        update = mh / (jnp.sqrt(vh) + ctx["eps"])
        if ctx["weight_decay"] != 0.0 and ctx["adam_w_mode"]:
            update = update + ctx["weight_decay"] * p32
        return p32 - ctx["lr"] * update, new_slots


class FusedAdamW(FusedAdam):
    name = "adamw"
    defaults = {**FusedAdam.defaults, "adam_w_mode": True}


class DeepSpeedCPUAdam(FusedAdam):
    """Host-offloaded Adam (reference ``csrc/adam/cpu_adam.cpp``): the engine
    places this optimizer's state in host memory (ZeRO-Offload); update math
    is identical. The native AVX path lives in csrc/cpu_adam (see ops/csrc)."""

    name = "cpu_adam"


class FusedLamb(Optimizer):
    """LAMB (reference ``csrc/lamb/fused_lamb_cuda_kernel.cu``): Adam update
    rescaled per-tensor by trust ratio ||p|| / ||update||."""

    name = "lamb"
    defaults = dict(lr=1e-3, betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
                    bias_correction=True, max_coeff=10.0, min_coeff=0.01)

    def _init_slot(self, p):
        z = jnp.zeros(p.shape, jnp.float32)
        return {"m": z, "v": z}

    def _update_one(self, g, p, slots, ctx):
        b1, b2 = ctx["betas"]
        p32 = p.astype(jnp.float32)
        m = b1 * slots["m"] + (1 - b1) * g
        v = b2 * slots["v"] + (1 - b2) * jnp.square(g)
        if ctx["bias_correction"]:
            mh = m / (1 - jnp.power(b1, ctx["step"]))
            vh = v / (1 - jnp.power(b2, ctx["step"]))
        else:
            mh, vh = m, v
        update = mh / (jnp.sqrt(vh) + ctx["eps"]) + ctx["weight_decay"] * p32
        w_norm = jnp.linalg.norm(p32)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0),
                          jnp.clip(w_norm / u_norm, ctx["min_coeff"], ctx["max_coeff"]), 1.0)
        return p32 - ctx["lr"] * trust * update, {"m": m, "v": v}


class FusedLion(Optimizer):
    """Lion (reference ``csrc/lion``): sign-of-momentum update."""

    name = "lion"
    defaults = dict(lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0)

    def _init_slot(self, p):
        return {"m": jnp.zeros(p.shape, jnp.float32)}

    def _update_one(self, g, p, slots, ctx):
        b1, b2 = ctx["betas"]
        p32 = p.astype(jnp.float32)
        update = jnp.sign(b1 * slots["m"] + (1 - b1) * g)
        if ctx["weight_decay"] != 0.0:
            update = update + ctx["weight_decay"] * p32
        m = b2 * slots["m"] + (1 - b2) * g
        return p32 - ctx["lr"] * update, {"m": m}


class DeepSpeedCPULion(FusedLion):
    name = "cpu_lion"


class FusedAdagrad(Optimizer):
    """Adagrad (reference ``csrc/adagrad/cpu_adagrad.cpp``)."""

    name = "adagrad"
    defaults = dict(lr=1e-2, eps=1e-10, weight_decay=0.0)

    def _init_slot(self, p):
        return {"acc": jnp.zeros(p.shape, jnp.float32)}

    def _update_one(self, g, p, slots, ctx):
        p32 = p.astype(jnp.float32)
        if ctx["weight_decay"] != 0.0:
            g = g + ctx["weight_decay"] * p32
        acc = slots["acc"] + jnp.square(g)
        return p32 - ctx["lr"] * g / (jnp.sqrt(acc) + ctx["eps"]), {"acc": acc}


class DeepSpeedCPUAdagrad(FusedAdagrad):
    name = "cpu_adagrad"


class SGD(Optimizer):
    name = "sgd"
    defaults = dict(lr=1e-2, momentum=0.0, weight_decay=0.0, nesterov=False)

    def _init_slot(self, p):
        return {"m": jnp.zeros(p.shape, jnp.float32)}

    def _update_one(self, g, p, slots, ctx):
        p32 = p.astype(jnp.float32)
        if ctx["weight_decay"] != 0.0:
            g = g + ctx["weight_decay"] * p32
        m = ctx["momentum"] * slots["m"] + g
        step_dir = g + ctx["momentum"] * m if ctx["nesterov"] else m
        return p32 - ctx["lr"] * step_dir, {"m": m}


class OneBitAdam(FusedAdam):
    """1-bit Adam semantics (reference ``runtime/fp16/onebit/adam.py:14``):
    exact Adam during warmup; in the compressed stage the variance is frozen
    and the momentum update is sign-compressed with an error-feedback buffer.
    (Cross-replica compression of the comm itself is the quantized-collectives
    layer's job; this preserves the optimizer's numerics contract.)"""

    name = "onebit_adam"
    defaults = {**FusedAdam.defaults, "freeze_step": 100_000, "cuda_aware": False,
                "comm_backend_name": "xla"}

    def _init_slot(self, p):
        slot = super()._init_slot(p)
        slot["error"] = jnp.zeros(p.shape, jnp.float32)
        return slot

    def _update_one(self, g, p, slots, ctx):
        b1, b2 = ctx["betas"]
        p32 = p.astype(jnp.float32)
        warm = ctx["step"] <= ctx["freeze_step"]
        m_new = b1 * slots["m"] + (1 - b1) * g
        v_new = jnp.where(warm, b2 * slots["v"] + (1 - b2) * jnp.square(g), slots["v"])
        # compressed stage: sign(m + error) with error feedback
        corrected = m_new + slots["error"]
        scale = jnp.mean(jnp.abs(corrected))
        compressed = scale * jnp.sign(corrected)
        error = jnp.where(warm, slots["error"], corrected - compressed)
        m_eff = jnp.where(warm, m_new, compressed)
        if ctx["bias_correction"]:
            mh = m_eff / (1 - jnp.power(b1, ctx["step"]))
            vh = v_new / (1 - jnp.power(b2, ctx["step"]))
        else:
            mh, vh = m_eff, v_new
        update = mh / (jnp.sqrt(vh) + ctx["eps"])
        if ctx["weight_decay"] != 0.0 and ctx["adam_w_mode"]:
            update = update + ctx["weight_decay"] * p32
        return p32 - ctx["lr"] * update, {"m": m_eff, "v": v_new, "error": error}


class ZeroOneAdam(OneBitAdam):
    """0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py``).

    Defining policy implemented here: the variance updates at exponentially
    sparsifying intervals — interval doubles after every ``var_update_scaler``
    occurrences — and freezes entirely at ``var_freeze_step``
    (reference ``zoadam.py`` var_interval/var_counter bookkeeping, computed
    here in closed form so the schedule works under jit with a traced step).
    Momentum keeps the sign-compression + error-feedback path from
    OneBitAdam; the engine's compressed stage carries the actual 1-bit
    collective. The reference's local-step accumulator (``lrs`` /
    ``local_step_scaler``) is a pipeline-specific comm policy not modeled by
    the compiled step; its hyperparameters are accepted for config parity.
    """

    name = "zero_one_adam"
    defaults = {**OneBitAdam.defaults, "var_freeze_step": 100_000,
                "var_update_scaler": 16, "local_step_scaler": 32678,
                "local_step_clipper": 16}

    def _update_one(self, g, p, slots, ctx):
        b1, b2 = ctx["betas"]
        p32 = p.astype(jnp.float32)
        t = ctx["step"]
        s = float(max(int(ctx["var_update_scaler"]), 1))
        # interval level j: intervals 1,2,4,... each lasting s occurrences;
        # the step entering level j is t_j = s*(2^j - 1), so
        # j = floor(log2(t/s + 1)) and var updates fire when the offset into
        # the level is a multiple of 2^j.
        j = jnp.floor(jnp.log2(t / s + 1.0))
        interval = jnp.exp2(j)
        offset = t - s * (jnp.exp2(j) - 1.0)
        do_var = jnp.logical_and(jnp.mod(offset, interval) < 0.5,
                                 t <= ctx["var_freeze_step"])
        m_new = b1 * slots["m"] + (1 - b1) * g
        v_new = jnp.where(do_var, b2 * slots["v"] + (1 - b2) * jnp.square(g),
                          slots["v"])
        # sign compression with error feedback on the momentum (0/1 Adam
        # compresses from the start, no warmup stage)
        corrected = m_new + slots["error"]
        scale = jnp.mean(jnp.abs(corrected))
        compressed = scale * jnp.sign(corrected)
        error = corrected - compressed
        update = compressed / (jnp.sqrt(v_new) + ctx["eps"])
        if ctx["weight_decay"] != 0.0 and ctx["adam_w_mode"]:
            update = update + ctx["weight_decay"] * p32
        return p32 - ctx["lr"] * update, {"m": compressed, "v": v_new,
                                          "error": error}


class OneBitLamb(FusedLamb):
    """1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py``)."""

    name = "onebit_lamb"
    defaults = {**FusedLamb.defaults, "freeze_step": 100_000}

    def _init_slot(self, p):
        slot = super()._init_slot(p)
        slot["error"] = jnp.zeros(p.shape, jnp.float32)
        return slot

    def _update_one(self, g, p, slots, ctx):
        warm = ctx["step"] <= ctx["freeze_step"]
        corrected = g + slots["error"]
        scale = jnp.mean(jnp.abs(corrected))
        compressed = scale * jnp.sign(corrected)
        error = jnp.where(warm, slots["error"], corrected - compressed)
        g_eff = jnp.where(warm, g, compressed)
        new_p, new_slots = super()._update_one(g_eff, p, slots, ctx)
        new_slots["error"] = error
        return new_p, new_slots


OPTIMIZER_REGISTRY = {
    "adam": FusedAdam,
    "adamw": FusedAdamW,
    "fusedadam": FusedAdam,
    "fusedadamw": FusedAdamW,
    "deepspeedcpuadam": DeepSpeedCPUAdam,
    "cpuadam": DeepSpeedCPUAdam,
    "lamb": FusedLamb,
    "fusedlamb": FusedLamb,
    "lion": FusedLion,
    "fusedlion": FusedLion,
    "deepspeedcpulion": DeepSpeedCPULion,
    "cpulion": DeepSpeedCPULion,
    "adagrad": FusedAdagrad,
    "deepspeedcpuadagrad": DeepSpeedCPUAdagrad,
    "cpuadagrad": DeepSpeedCPUAdagrad,
    "sgd": SGD,
    "onebitadam": OneBitAdam,
    "onebitlamb": OneBitLamb,
    "zerooneadam": ZeroOneAdam,
}


def build_optimizer(name: str, params_dict: Optional[dict] = None) -> Optimizer:
    """Instantiate by DeepSpeed config name (reference
    ``runtime/engine.py:1322 _configure_basic_optimizer``)."""
    key = name.lower().replace("_", "").replace("-", "")
    if key not in OPTIMIZER_REGISTRY:
        raise ValueError(f"Unknown optimizer {name!r}; known: {sorted(set(OPTIMIZER_REGISTRY))}")
    hyper = dict(params_dict or {})
    # translate torch-style names
    if "betas" in hyper:
        hyper["betas"] = tuple(hyper["betas"])
    hyper.pop("torch_adam", None)
    hyper.pop("fused", None)
    return OPTIMIZER_REGISTRY[key](**hyper)
