"""Fused, vocab-chunked softmax cross-entropy.

The naive LM loss materializes ``(B, S, V)`` logits (bf16) plus an fp32 copy
for the log-softmax — at GPT-2's 50k vocab that is the single largest
activation in the step (gigabytes at batch 16) and a pure-HBM-traffic
bottleneck in the loss backward. This op never materializes the full logits:
the lm-head matmul, online logsumexp, and label gather run chunk-by-chunk
over the vocab inside a ``lax.scan`` (forward), and the backward recomputes
each chunk's logits to form ``dlogits`` on the fly, feeding the ``dh`` /
``dW`` matmuls per chunk.

Reference analog: DeepSpeed tiles exactly this kind of projection+loss to
bound memory (``runtime/zero/tiling.py`` TiledLinear, and the
sequence-parallel vocab cross-entropy ``sequence/cross_entropy.py:59``);
the TPU-native version fuses it into the compiled step instead of wrapping
modules.

Numerics: matmuls run in the input dtype (bf16 on TPU) with fp32
accumulation; logsumexp/probabilities are fp32. Gradients match the unfused
fp32 loss to bf16-matmul precision.
"""

import functools

import jax
import jax.numpy as jnp

DEFAULT_CHUNKS = 8


def _pad_vocab(w, v, n_chunks):
    """Pad vocab dim (leading) to a multiple of n_chunks."""
    vp = (v + n_chunks - 1) // n_chunks * n_chunks
    if vp != v:
        w = jnp.pad(w, ((0, vp - v), (0, 0)))
    return w, vp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def chunked_softmax_xent(h, w, labels, n_chunks=DEFAULT_CHUNKS, softcap=0.0):
    """Per-token negative log-likelihood without materializing logits.

    h: (N, E) activations; w: (V, E) output embedding (logits = h @ w.T);
    labels: (N,) int32. Returns nll (N,) fp32.
    ``softcap``: Gemma-2 final-logit softcapping, applied per chunk before
    the online logsumexp (the backward differentiates through the tanh).
    """
    nll, _ = _xent_fwd_core(h, w, labels, n_chunks, softcap)
    return nll


def _xent_fwd_core(h, w, labels, n_chunks, softcap=0.0):
    n, e = h.shape
    v = w.shape[0]
    wp, vp = _pad_vocab(w, v, n_chunks)
    c = vp // n_chunks
    w_chunks = wp.reshape(n_chunks, c, e)

    def body(carry, inp):
        m, s, ll = carry
        w_c, idx = inp
        logits = jax.lax.dot_general(h, w_c, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)  # (N, C)
        if softcap:
            logits = softcap * jnp.tanh(logits / softcap)
        col = idx * c + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(col < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=1)
        # label logit if the label falls in this chunk
        in_chunk = (labels >= idx * c) & (labels < (idx + 1) * c)
        local = jnp.clip(labels - idx * c, 0, c - 1)
        ll = ll + jnp.where(in_chunk,
                            jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0],
                            0.0)
        return (m_new, s, ll), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    ll0 = jnp.zeros((n,), jnp.float32)
    (m, s, ll), _ = jax.lax.scan(body, (m0, s0, ll0),
                                 (w_chunks, jnp.arange(n_chunks, dtype=jnp.int32)))
    lse = m + jnp.log(s)
    return lse - ll, lse


def _xent_fwd_rule(h, w, labels, n_chunks, softcap):
    nll, lse = _xent_fwd_core(h, w, labels, n_chunks, softcap)
    return nll, (h, w, labels, lse)


def _xent_bwd_rule(n_chunks, softcap, res, g):
    h, w, labels, lse = res
    n, e = h.shape
    v = w.shape[0]
    wp, vp = _pad_vocab(w, v, n_chunks)
    c = vp // n_chunks
    w_chunks = wp.reshape(n_chunks, c, e)
    gf = g.astype(jnp.float32)

    def body(dh, inp):
        w_c, idx = inp
        logits = jax.lax.dot_general(h, w_c, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)  # (N, C)
        if softcap:
            capped = softcap * jnp.tanh(logits / softcap)
            dcap = 1.0 - jnp.square(capped / softcap)   # d(capped)/d(logits)
            logits = capped
        col = idx * c + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        p = jnp.exp(logits - lse[:, None])
        p = jnp.where(col < v, p, 0.0)
        onehot = (col == labels[:, None]).astype(jnp.float32)
        dlogits = (p - onehot) * gf[:, None]                          # (N, C)
        if softcap:
            dlogits = dlogits * dcap
        dlogits = dlogits.astype(h.dtype)
        dh = dh + jax.lax.dot_general(dlogits, w_c, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dw_c = jax.lax.dot_general(dlogits, h, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)  # (C, E)
        return dh, dw_c

    dh, dw_p = jax.lax.scan(body, jnp.zeros((n, e), jnp.float32),
                            (w_chunks, jnp.arange(n_chunks, dtype=jnp.int32)))
    dw = dw_p.reshape(vp, e)[:v]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


chunked_softmax_xent.defvjp(_xent_fwd_rule, _xent_bwd_rule)


def lm_cross_entropy(h, w, labels, loss_mask=None, n_chunks=DEFAULT_CHUNKS,
                     transpose_w=False, softcap=0.0):
    """Mean cross-entropy over (B, S) tokens from final hidden states.

    h: (B, S, E); w: (V, E) tied embedding (or (E, V) with transpose_w);
    labels: (B, S). Never materializes (B, S, V).
    """
    b, s, e = h.shape
    if transpose_w:
        w = w.T
    nll = chunked_softmax_xent(h.reshape(b * s, e), w, labels.reshape(-1), n_chunks,
                               softcap)
    nll = nll.reshape(b, s)
    if loss_mask is None:
        return jnp.mean(nll)
    return jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
