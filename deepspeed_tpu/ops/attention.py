"""Attention op dispatch.

Single call site for all models: picks the best implementation for the
platform (Pallas flash attention on TPU, fused-einsum reference path on CPU),
the way the reference routes attention through op builders
(``deepspeed/ops/transformer/inference/ds_attention.py``).

Ulysses sequence parallelism (reference ``deepspeed/sequence/layer.py:145``)
is expressed here as sharding constraints: activations arrive sequence-sharded
``P(batch, 'seq', ...)``; constraining q/k/v to head-sharded
``P(batch, None, 'seq', None)`` makes XLA emit exactly the all-to-all that
``_SeqAllToAll`` hand-codes, riding ICI.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import groups

_FALLBACK_WARNED = set()


def _use_pallas() -> bool:
    import os
    if os.environ.get("DS_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    return jax.default_backend() == "tpu"


def window_mask(q_pos, k_pos, window):
    """Sliding-window visibility: key k is visible to query q iff
    q - k < window; window may be traced, and window <= 0 means global
    (the sentinel per-layer local/global patterns scan over). Single source
    of the convention for all three attention engines."""
    w = jnp.asarray(window, jnp.int32)
    return (q_pos - k_pos < w) | (w <= 0)


def reference_attention(q, k, v, *, causal=True, bias=None, segment_ids=None, scale=None,
                        window=None, softcap=0.0):
    """Plain XLA attention: (B, S, H, D) x (B, S, KVH, D) -> (B, S, H, D).

    Handles GQA by repeating kv heads. fp32 softmax for stability.
    ``window``: sliding-window width — query q sees keys in (q-window, q].
    May be a traced scalar (per-layer local/global patterns under scan);
    window <= 0 means global.
    ``softcap``: Gemma-2 attention-logit softcapping, applied to the scaled
    logits (+ bias) BEFORE masking, matching HF's order.
    """
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    if softcap:
        logits = softcap * jnp.tanh(logits / softcap)
    sk = k.shape[1]
    if causal or window is not None:
        q_pos = jnp.arange(sq)[:, None] + (sk - sq)
        k_pos = jnp.arange(sk)[None, :]
        mask = q_pos >= k_pos if causal else jnp.ones((sq, sk), bool)
        if window is not None:
            mask = mask & window_mask(q_pos, k_pos, window)
        logits = jnp.where(mask[None, None, :, :], logits, jnp.finfo(jnp.float32).min)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]  # (B, Sq, Sk)
        logits = jnp.where(seg_mask[:, None, :, :], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _alibi_bias_from_slopes(slopes, sq, sk):
    """(H,) slopes → (1, H, Sq, Sk) additive bias (XLA fallback paths)."""
    q_pos = jnp.arange(sq) + (sk - sq)
    k_pos = jnp.arange(sk)
    rel = (k_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
    return (jnp.asarray(slopes, jnp.float32)[:, None, None] * rel)[None]


def _reference_with_slopes(q, k, v, causal, bias, alibi_slopes, segment_ids,
                           scale, window, softcap=0.0):
    """Single fallback entry: expand ALiBi slopes to a bias and run the XLA
    reference path (keeps the expansion in exactly one place)."""
    if alibi_slopes is not None and bias is None:
        bias = _alibi_bias_from_slopes(alibi_slopes, q.shape[1], k.shape[1])
    return reference_attention(q, k, v, causal=causal, bias=bias,
                               segment_ids=segment_ids, scale=scale,
                               window=window, softcap=softcap)


def _ulysses_exchange(mesh, q, k, v, local_attn):
    """The Ulysses head/seq exchange around a local attention computation.

    Under plain SPMD jit, ``with_sharding_constraint`` pins q/k/v to
    head-sharded and the output back to seq-sharded; XLA derives the two
    all-to-alls from the spec flip (reference ``sequence/layer.py:145``
    hand-codes them as ``_SeqAllToAll``).

    Inside a partial-manual shard_map region (the ZeRO++ quantized-collective
    step is manual over the data-like axes) the ``seq`` axis is Auto-typed
    and sharding constraints may not mention it — there the exchange is
    expressed with sharding-in-types: ``explicit_axes`` locally retypes
    ``seq`` Explicit, ``reshard`` forces the seq->head all-to-all, the local
    attention runs back under ``auto_axes`` (so attention impls need no
    explicit-mode sharding rules), and a second ``reshard`` forces the
    head->seq all-to-all out.
    """
    head_spec = P(groups.BATCH_AXES, None, "seq", None)
    out_spec = P(groups.BATCH_AXES, "seq", None, None)

    from ..parallel.sharding import current_manual_axes
    if not current_manual_axes():
        def pin(x, spec):
            return jax.lax.with_sharding_constraint(x, jax.NamedSharding(mesh, spec))
        out = local_attn(pin(q, head_spec), pin(k, head_spec), pin(v, head_spec))
        return pin(out, out_spec)

    seq_in = P(None, "seq", None, None)
    head = P(None, None, "seq", None)

    def inner(q, k, v):
        q, k, v = (jax.sharding.reshard(x, head) for x in (q, k, v))
        out = jax.sharding.auto_axes(local_attn, axes=("seq",),
                                     out_sharding=head)(q, k, v)
        return jax.sharding.reshard(out, seq_in)

    return jax.sharding.explicit_axes(
        inner, axes=("seq",), in_sharding=(seq_in, seq_in, seq_in))(q, k, v)


def multihead_attention(q, k, v, *, causal=True, bias=None, segment_ids=None, scale=None,
                        window=None, alibi_slopes=None, impl: Optional[str] = None,
                        softcap=0.0):
    """Dispatching attention entry point.

    q: (B, S, H, D); k/v: (B, S, KVH, D). Returns (B, S, H, D).
    impl: None (auto) | "reference" | "flash" | "ulysses"
    window: sliding-window width (Mistral/GPT-Neo local attention). A
    static int >= S is a no-op (dropped so flash stays eligible); a traced
    scalar or a binding window routes to the reference path.
    alibi_slopes: (H,) per-head ALiBi slopes — handled IN-KERNEL by the
    flash path (no O(S^2) bias tensor); expanded to a bias only for the
    XLA fallback. Treated as non-differentiable constants. Mutually
    exclusive with an explicit ``bias``.
    """
    if bias is not None and alibi_slopes is not None:
        raise ValueError(
            "pass either an explicit additive bias or alibi_slopes, not "
            "both (the slopes would be silently dropped)")
    if isinstance(window, int) and (window >= q.shape[1] or window <= 0):
        window = None   # cannot bind (or the <=0 "global" sentinel)
    mesh = groups.get_mesh() if groups.mesh_is_initialized() else None
    seq_sharded = mesh is not None and mesh.shape.get("seq", 1) > 1

    if impl == "ring":
        from ..sequence.ring_attention import ring_attention
        if not causal:
            raise NotImplementedError("ring attention is causal-only")
        if seq_sharded:
            if bias is not None or softcap:
                raise NotImplementedError(
                    "ring attention takes ALiBi as slopes (not an explicit "
                    "bias tensor) and has no logit softcapping; use Ulysses "
                    "SP or attn_impl='reference'")
            return ring_attention(q, k, v, scale=scale, window=window,
                                  alibi_slopes=alibi_slopes,
                                  segment_ids=segment_ids)
        # no seq axis: plain local attention
        return _reference_with_slopes(q, k, v, causal, bias, alibi_slopes,
                                      segment_ids, scale, window, softcap)

    # flash handles static-int causal windows in-kernel (block skipping);
    # traced per-layer windows (scan over local/global patterns) cannot be
    # static and stay on the reference path
    flash_window_ok = window is None or (isinstance(window, int) and causal)
    if impl == "flash" and (bias is not None or not flash_window_ok or softcap):
        raise NotImplementedError(
            "the Pallas flash kernel does not take an additive attention "
            "bias tensor, a traced/non-causal sliding window, or logit "
            "softcapping; use attn_impl='reference' (auto dispatch already "
            "routes these there)")

    def dispatch(q, k, v):
        if impl == "flash" or (impl is None and _use_pallas() and q.shape[1] >= 128 and
                               q.shape[3] in (64, 128, 256) and bias is None and
                               not softcap and flash_window_ok):
            try:
                from .pallas.flash_attention import flash_attention
                return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                                       scale=scale, alibi_slopes=alibi_slopes,
                                       window=window)
            except Exception as e:
                # A silent fallback here would quietly cost O(S^2) memory and
                # a large fraction of peak throughput — warn loudly, once per
                # shape.
                global _FALLBACK_WARNED
                key = (q.shape, str(q.dtype))
                if key not in _FALLBACK_WARNED:
                    _FALLBACK_WARNED.add(key)
                    import logging
                    logging.getLogger("DeepSpeedTPU").warning(
                        "Pallas flash attention FAILED for shape %s (%s: %s); "
                        "falling back to O(S^2) XLA attention. Performance "
                        "will suffer — set DS_TPU_DISABLE_PALLAS=1 to silence.",
                        q.shape, type(e).__name__, e)
                if impl == "flash":
                    raise
        return _reference_with_slopes(q, k, v, causal, bias, alibi_slopes,
                                      segment_ids, scale, window, softcap)

    if seq_sharded:
        # Ulysses: swap sequence-sharding for head-sharding around the local
        # attention; the exchange lowers to all-to-all over the seq axis.
        return _ulysses_exchange(mesh, q, k, v, dispatch)
    return dispatch(q, k, v)


def decode_attention(q, k_cache, v_cache, cache_len, *, bias=None, scale=None,
                     window=None, softcap=0.0):
    """Decode/prefill attention against a (B, S_max, KVH, D) KV cache.

    q: (B, S_new, H, D) — the S_new query tokens occupy cache slots
    [cache_len - S_new, cache_len); each query attends causally: key slot k
    is visible to query i iff k < cache_len - S_new + i + 1.
    bias: optional additive (B, H, S_new, S_max) attention bias (ALiBi);
    bias routes around the fused Pallas kernel.
    window: sliding-window width (query at slot p sees slots (p-window, p]);
    may be traced, <= 0 means global.

    Single-token decode (S_new == 1) over a LONG cache routes through the
    fused Pallas kernel (``ops/pallas/decode_attention.py`` — the v1
    fused-decode analog of the reference's ``softmax_context``), which never
    materializes the (B, H, S_max) logits. Both forms are HBM-bound
    streaming the cache, so the crossover is late (measured ≥8k on v5e);
    shorter caches and prefill chunks use the batched XLA einsum below.
    """
    b, s_new, h, d = q.shape
    if isinstance(window, int) and window >= k_cache.shape[1]:
        window = None   # cannot bind within this cache
    if (s_new == 1 and bias is None and window is None and not softcap
            and _use_pallas()
            and k_cache.shape[1] >= 8192
            and k_cache.shape[1] % 128 == 0 and d % 64 == 0
            and h % k_cache.shape[2] == 0):
        try:
            from .pallas.decode_attention import fused_decode_attention
            block = min(512, k_cache.shape[1])
            if k_cache.shape[1] % block:
                block = 128
            out = fused_decode_attention(q[:, 0], k_cache, v_cache, cache_len,
                                         scale=scale, block=block)
            return out[:, None]
        except Exception as e:
            key = ("decode", q.shape, str(q.dtype))
            if key not in _FALLBACK_WARNED:
                _FALLBACK_WARNED.add(key)
                import logging
                logging.getLogger("DeepSpeedTPU").warning(
                    "Pallas fused decode FAILED for %s (%s: %s); using XLA "
                    "masked attention.", q.shape, type(e).__name__, e)
    kvh = k_cache.shape[2]
    if kvh != h:
        rep = h // kvh
        k_cache = jnp.repeat(k_cache, rep, axis=2)
        v_cache = jnp.repeat(v_cache, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    if softcap:
        logits = softcap * jnp.tanh(logits / softcap)
    q_pos = (cache_len[:, None] - s_new) + jnp.arange(s_new)[None, :]      # (B, S_new)
    k_pos = jnp.arange(k_cache.shape[1])[None, None, :]                    # (1, 1, S_max)
    mask = k_pos <= q_pos[:, :, None]                                      # (B, S_new, S_max)
    if window is not None:
        mask = mask & window_mask(q_pos[:, :, None], k_pos, window)
    logits = jnp.where(mask[:, None, :, :], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache)
