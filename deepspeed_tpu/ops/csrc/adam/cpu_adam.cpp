// Host-side vectorized Adam for ZeRO-Offload (DeepSpeedCPUAdam analog).
//
// Counterpart of the reference's csrc/adam/cpu_adam_impl.cpp + simd.h:
// AVX2/AVX512-vectorized AdamW update over contiguous fp32 buffers, run on
// host CPU while the accelerator computes the next step's forward/backward.
// Vectorization is delegated to the compiler (-O3 -march=native -ffast-math
// auto-vectorizes this loop to AVX512 where available), which matches the
// hand-rolled intrinsics of the reference within measurement noise on
// stream-bound updates.
//
// C ABI: ds_cpu_adam_step operates on raw fp32 pointers (params, grads,
// exp_avg, exp_avg_sq), matching the reference's flat-buffer contract.

#include <cmath>
#include <cstdint>

extern "C" {

void ds_cpu_adam_step(float* params,
                      const float* grads,
                      float* exp_avg,
                      float* exp_avg_sq,
                      int64_t n,
                      int64_t step,
                      float lr,
                      float beta1,
                      float beta2,
                      float eps,
                      float weight_decay,
                      int adamw_mode,
                      int bias_correction) {
    float bc1 = 1.0f, bc2 = 1.0f;
    if (bias_correction) {
        bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
        bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
    }
    const float inv_bc1 = 1.0f / bc1;
    const float inv_bc2 = 1.0f / bc2;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;

    if (adamw_mode) {
#pragma omp simd
        for (int64_t i = 0; i < n; ++i) {
            const float g = grads[i];
            const float m = beta1 * exp_avg[i] + one_minus_b1 * g;
            const float v = beta2 * exp_avg_sq[i] + one_minus_b2 * g * g;
            exp_avg[i] = m;
            exp_avg_sq[i] = v;
            const float mh = m * inv_bc1;
            const float vh = v * inv_bc2;
            const float update = mh / (std::sqrt(vh) + eps) + weight_decay * params[i];
            params[i] -= lr * update;
        }
    } else {
#pragma omp simd
        for (int64_t i = 0; i < n; ++i) {
            const float g = grads[i] + weight_decay * params[i];
            const float m = beta1 * exp_avg[i] + one_minus_b1 * g;
            const float v = beta2 * exp_avg_sq[i] + one_minus_b2 * g * g;
            exp_avg[i] = m;
            exp_avg_sq[i] = v;
            const float mh = m * inv_bc1;
            const float vh = v * inv_bc2;
            params[i] -= lr * (mh / (std::sqrt(vh) + eps));
        }
    }
}

void ds_cpu_adagrad_step(float* params,
                         const float* grads,
                         float* exp_avg_sq,
                         int64_t n,
                         float lr,
                         float eps,
                         float weight_decay) {
#pragma omp simd
    for (int64_t i = 0; i < n; ++i) {
        const float g = grads[i] + weight_decay * params[i];
        const float acc = exp_avg_sq[i] + g * g;
        exp_avg_sq[i] = acc;
        params[i] -= lr * g / (std::sqrt(acc) + eps);
    }
}

void ds_cpu_lion_step(float* params,
                      const float* grads,
                      float* exp_avg,
                      int64_t n,
                      float lr,
                      float beta1,
                      float beta2,
                      float weight_decay) {
#pragma omp simd
    for (int64_t i = 0; i < n; ++i) {
        const float g = grads[i];
        const float c = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        const float sign = c > 0.0f ? 1.0f : (c < 0.0f ? -1.0f : 0.0f);
        params[i] -= lr * (sign + weight_decay * params[i]);
        exp_avg[i] = beta2 * exp_avg[i] + (1.0f - beta2) * g;
    }
}

}  // extern "C"
