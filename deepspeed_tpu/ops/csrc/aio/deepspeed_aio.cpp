// Async file I/O engine for tensor swapping (DeepNVMe analog).
//
// TPU-native counterpart of the reference's csrc/aio/py_lib
// (deepspeed_py_aio_handle.cpp / deepspeed_aio_thread.cpp): a pool of worker
// threads servicing pread/pwrite requests against NVMe-backed files, used by
// the ZeRO-Offload/Infinity swap layer. The reference uses libaio; this uses
// a portable thread pool issuing positional I/O (optionally O_DIRECT), which
// saturates NVMe queues just as well for the large sequential blocks the
// swapper issues, and avoids a hard libaio dependency.
//
// C ABI (ctypes-friendly): all functions exported with ds_aio_ prefix.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
    int64_t id;
    bool write;
    std::string path;
    void* buf;
    int64_t nbytes;
    int64_t offset;
};

struct AioHandle {
    std::vector<std::thread> workers;
    std::deque<Request> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::atomic<int64_t> submitted{0};
    std::atomic<int64_t> completed{0};
    std::atomic<int64_t> errors{0};
    int block_size;
    bool use_direct;
    bool stop = false;

    AioHandle(int num_threads, int block_size_, bool use_direct_)
        : block_size(block_size_), use_direct(use_direct_) {
        for (int i = 0; i < num_threads; ++i) {
            workers.emplace_back([this] { this->worker_loop(); });
        }
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv.notify_all();
        for (auto& t : workers) t.join();
    }

    void worker_loop() {
        for (;;) {
            Request req;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv.wait(lk, [this] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                req = queue.front();
                queue.pop_front();
            }
            if (do_io(req) != 0) errors.fetch_add(1);
            completed.fetch_add(1);
            done_cv.notify_all();
        }
    }

    int do_io(const Request& req) {
        int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
        bool direct = false;
#ifdef O_DIRECT
        // unaligned offsets cannot use O_DIRECT at all — open buffered
        if (use_direct && req.offset % 4096 == 0) { flags |= O_DIRECT; direct = true; }
#endif
        int fd = ::open(req.path.c_str(), flags, 0644);
        if (fd < 0 && direct) {  // filesystem may not support O_DIRECT
            fd = ::open(req.path.c_str(), req.write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
            direct = false;
        }
        if (fd < 0) return -1;
        int rc = direct ? do_io_direct(fd, req) : do_io_buffered(fd, req);
        ::close(fd);
        return rc;
    }

    int do_io_buffered(int fd, const Request& req) {
        int64_t remaining = req.nbytes;
        char* p = static_cast<char*>(req.buf);
        int64_t off = req.offset;
        // chunk into block_size pieces so queues interleave across workers
        while (remaining > 0) {
            int64_t n = remaining < block_size ? remaining : block_size;
            ssize_t r = req.write ? ::pwrite(fd, p, n, off) : ::pread(fd, p, n, off);
            if (r < 0) return -1;
            if (r == 0) break;  // EOF on read
            p += r;
            off += r;
            remaining -= r;
        }
        return remaining == 0 ? 0 : (req.write ? -1 : 0);
    }

    // O_DIRECT path: user buffers are arbitrary numpy memory, so stage
    // through a page-aligned bounce buffer (the pinned-buffer-manager role
    // of the reference's deepspeed_pin_tensor.cpp). Only reached for
    // sector-aligned offsets (do_io opens unaligned requests buffered); a
    // ragged tail is completed with an aligned full-sector transfer for
    // writes (file extended, then truncated back).
    int do_io_direct(int fd, const Request& req) {
        constexpr int64_t kAlign = 4096;
        void* bounce = nullptr;
        int64_t buf_len = block_size < kAlign ? kAlign : block_size;
        if (posix_memalign(&bounce, kAlign, buf_len) != 0) return -1;
        char* user = static_cast<char*>(req.buf);
        int64_t off = req.offset;
        int64_t remaining = req.nbytes;
        int rc = 0;
        while (remaining > 0 && rc == 0) {
            int64_t n = remaining < buf_len ? remaining : buf_len;
            int64_t n_aligned = (n + kAlign - 1) / kAlign * kAlign;
            if (req.write) {
                memcpy(bounce, user, n);
                if (n_aligned > n) memset(static_cast<char*>(bounce) + n, 0, n_aligned - n);
                ssize_t r = ::pwrite(fd, bounce, n_aligned, off);
                if (r != n_aligned) { rc = -1; break; }
            } else {
                ssize_t r = ::pread(fd, bounce, n_aligned, off);
                if (r < n) { rc = -1; break; }  // short read of live range
                memcpy(user, bounce, n);
            }
            user += n;
            off += n;
            remaining -= n;
        }
        free(bounce);
        if (rc == 0 && req.write && (req.nbytes % kAlign) != 0) {
            // trim the zero padding the last aligned sector appended
            if (::ftruncate(fd, req.offset + req.nbytes) != 0) rc = -1;
        }
        return rc;
    }

    int64_t submit(bool write, const char* path, void* buf, int64_t nbytes, int64_t offset) {
        int64_t id = submitted.fetch_add(1) + 1;
        {
            std::lock_guard<std::mutex> lk(mu);
            queue.push_back(Request{id, write, path, buf, nbytes, offset});
        }
        cv.notify_one();
        return id;
    }

    void wait_all() {
        std::unique_lock<std::mutex> lk(mu);
        done_cv.wait(lk, [this] {
            return completed.load() >= submitted.load();
        });
    }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int num_threads, int block_size, int use_direct) {
    if (num_threads < 1) num_threads = 1;
    if (block_size < 4096) block_size = 1 << 20;
    return new AioHandle(num_threads, block_size, use_direct != 0);
}

void ds_aio_handle_free(void* h) {
    delete static_cast<AioHandle*>(h);
}

int64_t ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes, int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(false, path, buf, nbytes, offset);
}

int64_t ds_aio_pwrite(void* h, const char* path, void* buf, int64_t nbytes, int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(true, path, buf, nbytes, offset);
}

void ds_aio_wait(void* h) {
    static_cast<AioHandle*>(h)->wait_all();
}

int64_t ds_aio_error_count(void* h) {
    return static_cast<AioHandle*>(h)->errors.load();
}

int64_t ds_aio_inflight(void* h) {
    auto* handle = static_cast<AioHandle*>(h);
    return handle->submitted.load() - handle->completed.load();
}

}  // extern "C"
