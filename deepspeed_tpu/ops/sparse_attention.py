"""Block-sparse attention.

Analog of ``deepspeed/ops/sparse_attention/`` (SparsityConfig family +
Triton matmul/softmax kernels): attention restricted to a block-level
sparsity pattern (fixed/ bigbird / bslongformer / dense). The pattern is a
(num_blocks, num_blocks) boolean layout; computation masks at block
granularity, which XLA turns into skipped tiles under fusion. (A Pallas
kernel that skips masked blocks entirely is the optimization path — the
splash-attention approach; this implementation is the semantics-complete
portable one.)
"""

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class SparsityConfig:
    num_heads: int
    block: int = 16
    different_layout_per_head: bool = False

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass
class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        return np.ones((n, n), bool)


@dataclasses.dataclass
class FixedSparsityConfig(SparsityConfig):
    """Reference FixedSparsityConfig: local window + periodic global blocks."""
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    attention: str = "bidirectional"   # or "unidirectional"

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        layout = np.zeros((n, n), bool)
        for i in range(n):
            # local window
            w0 = (i // self.num_local_blocks) * self.num_local_blocks
            layout[i, w0:w0 + self.num_local_blocks] = True
            # global columns: last block of each local window
            for g in range(self.num_global_blocks):
                col = g
                layout[i, col::self.num_local_blocks] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), bool))
        return layout


@dataclasses.dataclass
class BigBirdSparsityConfig(SparsityConfig):
    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    attention: str = "bidirectional"
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        layout = np.zeros((n, n), bool)
        half = self.num_sliding_window_blocks // 2
        rng = np.random.default_rng(self.seed)
        for i in range(n):
            layout[i, max(0, i - half):min(n, i + half + 1)] = True
            layout[i, :self.num_global_blocks] = True
            layout[:self.num_global_blocks, i] = True
            rnd = rng.choice(n, size=min(self.num_random_blocks, n), replace=False)
            layout[i, rnd] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), bool))
        return layout


@dataclasses.dataclass
class BSLongformerSparsityConfig(SparsityConfig):
    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)
    attention: str = "bidirectional"

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        layout = np.zeros((n, n), bool)
        half = self.num_sliding_window_blocks // 2
        for i in range(n):
            layout[i, max(0, i - half):min(n, i + half + 1)] = True
        for g in self.global_block_indices:
            layout[:, g] = True
            layout[g, :] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), bool))
        return layout


class SparseSelfAttention:
    """Reference-named module: applies attention under a block-sparse layout."""

    def __init__(self, sparsity_config: SparsityConfig, max_seq_length: int = 2048):
        self.config = sparsity_config
        self.max_seq_length = max_seq_length
        self._layouts = {}

    def layout(self, seq_len: int) -> jnp.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = jnp.asarray(self.config.make_layout(seq_len))
        return self._layouts[seq_len]

    def __call__(self, q, k, v, causal: Optional[bool] = None,
                 use_kernel: Optional[bool] = None):
        """q/k/v: (B, S, H, D) → (B, S, H, D).

        ``use_kernel`` (default: auto — TPU with a tile-divisible sequence)
        routes the forward through the block-skipping Pallas splash kernel
        (``ops/pallas/sparse_flash.py``): cost and memory scale with active
        blocks instead of S². The dense masked form remains the fallback
        and the backward pass."""
        s = q.shape[1]
        block = self.config.block
        assert s % block == 0, f"seq {s} not divisible by block {block}"
        is_causal = bool(causal or self.config.attention == "unidirectional")
        if use_kernel is None:
            import jax as _jax
            from .pallas.sparse_flash import TILE_Q
            use_kernel = (_jax.default_backend() == "tpu"
                          and s % TILE_Q == 0 and s >= TILE_Q)
        if use_kernel:
            from .pallas.sparse_flash import sparse_flash_attention
            return sparse_flash_attention(
                q, k, v, self.config.make_layout(s), layout_block=block,
                causal=is_causal)
        layout = self.layout(s)                                   # (n, n) blocks
        token_mask = jnp.repeat(jnp.repeat(layout, block, 0), block, 1)  # (S, S)
        if causal or self.config.attention == "unidirectional":
            token_mask = token_mask & (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :])
        d = q.shape[-1]
        h = q.shape[2]
        kvh = k.shape[2]
        if kvh != h:
            k = jnp.repeat(k, h // kvh, axis=2)
            v = jnp.repeat(v, h // kvh, axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * (d ** -0.5)
        logits = jnp.where(token_mask[None, None], logits, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
