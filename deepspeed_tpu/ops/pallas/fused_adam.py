"""Fused Adam update kernel over flat parameter buffers.

Analog of ``csrc/adam/multi_tensor_adam.cu`` (FusedAdam): one kernel updates
params + both moments in place. Under jit the tree_map optimizer already
fuses per-tensor; this kernel exists for the flat-buffer path (contiguous
ZeRO shards) where one launch covers the whole partition, and as the
Pallas-native counterpart the op-builder table points at.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() != "tpu"


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, hyper_ref,
                 p_out, m_out, v_out):
    lr = hyper_ref[0]
    b1 = hyper_ref[1]
    b2 = hyper_ref[2]
    eps = hyper_ref[3]
    wd = hyper_ref[4]
    step = hyper_ref[5]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    bc1 = 1.0 - jnp.power(b1, step)
    bc2 = 1.0 - jnp.power(b2, step)
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p
    p_out[:] = (p - lr * update).astype(p_out.dtype)
    m_out[:] = m
    v_out[:] = v


def fused_adam_flat(params, grads, exp_avg, exp_avg_sq, *, step, lr,
                    betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                    block: int = 1 << 16):
    """Flat fp32 buffers (N,) → (new_params, new_m, new_v). N % 128 == 0 for
    the TPU path; other sizes fall back to plain XLA."""
    n = params.size
    hyper = jnp.asarray([lr, betas[0], betas[1], eps, weight_decay, step], jnp.float32)
    if n % 128 != 0:
        # XLA fallback — identical math
        g = grads.astype(jnp.float32)
        m = betas[0] * exp_avg + (1 - betas[0]) * g
        v = betas[1] * exp_avg_sq + (1 - betas[1]) * g * g
        bc1 = 1 - betas[0] ** step
        bc2 = 1 - betas[1] ** step
        upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + weight_decay * params
        return (params - lr * upd).astype(params.dtype), m, v
    blk = min(block, n)
    while n % blk != 0:
        blk //= 2
    grid = (n // blk,)
    spec = pl.BlockSpec((blk,), lambda i: (i,))
    return pl.pallas_call(
        _adam_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct(params.shape, params.dtype),
                   jax.ShapeDtypeStruct(params.shape, jnp.float32),
                   jax.ShapeDtypeStruct(params.shape, jnp.float32)],
        interpret=_interpret(),
    )(params, grads, exp_avg, exp_avg_sq, hyper)
