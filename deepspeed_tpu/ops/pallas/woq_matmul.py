"""Fused weight-only-quantized matmul (mixed-input GEMM).

Analog of the reference's FP6/INT4 fused GEMMs
(``inference/v2/kernels/core_ops/cuda_linear/linear_kernels_cuda.cu``,
``cutlass_ops/mixed_gemm/``): the quantized weight streams from HBM in its
packed form and dequantizes TILE BY TILE in VMEM inside the matmul — the
full-size bf16 weight never exists, so decode-time linears keep the 4-8x
HBM-bandwidth win that is the point of weight-only quantization (the
previous ``QuantizedLinear`` dequantized the whole weight into HBM first:
``inference/quantization/layers.py:135`` in round-2's review).

Layouts (chosen so the kernel NEVER relayouts in VMEM — in-kernel
interleaves crash the tunneled Mosaic compiler, see the verify skill):
- scales are per (K-group, column): ``(K/g, N)`` f32 with g == the kernel's
  K-tile, so each k-step reads one ``(1, nt)`` scale row;
- int8: q ``(K, N)`` int8, used directly;
- int4: two nibble PLANES — byte row i holds w[i] (low nibble) and
  w[i + K/2] (high nibble): a k-tile reads a contiguous byte tile and picks
  its plane by grid index, no unpack interleave;
- fp6 (e3m2): codes distributed over FOUR planes — byte triple
  (B0, B1, B2)[i] packs codes for rows i, i+K/4, i+K/2, i+3K/4 — decoded
  arithmetically (sign/exp/mantissa), no codebook gather.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() != "tpu"


# ---- quantization (load time, plain XLA) ---------------------------------

def _group_scales(w, group, qmax):
    k, n = w.shape
    wg = w.reshape(k // group, group, n).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wg), axis=1)                  # (K/g, N)
    return jnp.maximum(absmax, 1e-10) / qmax


_FP6_MAX = 28.0


def quantize_woq(w, bits: int = 8, group_size: int = 128):
    """w: (K, N) → dict(q, scales, bits, group_size, shape).

    K must be divisible by group_size (and by 2*group_size for int4,
    4*group_size for fp6 — the plane layouts need aligned halves/quarters).
    """
    k, n = w.shape
    planes = {8: 1, 4: 2, 6: 4}[bits]
    if k % (group_size * planes):
        raise ValueError(f"K={k} must be divisible by {group_size * planes} "
                         f"for bits={bits}")
    if bits == 8:
        scales = _group_scales(w, group_size, 127.0)
        s_full = jnp.repeat(scales, group_size, axis=0)
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / s_full), -127, 127
                     ).astype(jnp.int8)
    elif bits == 4:
        scales = _group_scales(w, group_size, 7.0)
        s_full = jnp.repeat(scales, group_size, axis=0)
        qi = jnp.clip(jnp.round(w.astype(jnp.float32) / s_full), -7, 7
                      ).astype(jnp.int32)
        lo = qi[: k // 2] & 0xF
        hi = qi[k // 2:] & 0xF
        q = (lo | (hi << 4)).astype(jnp.int8)              # (K/2, N)
    elif bits == 6:
        scales = _group_scales(w, group_size, _FP6_MAX)
        s_full = jnp.repeat(scales, group_size, axis=0)
        x = (w.astype(jnp.float32) / s_full)
        codes = _fp6_encode(x)                             # (K, N) int32 6-bit
        kq = k // 4
        c0, c1, c2, c3 = (codes[i * kq:(i + 1) * kq] for i in range(4))
        word = c0 | (c1 << 6) | (c2 << 12) | (c3 << 18)
        q = jnp.stack([word & 0xFF, (word >> 8) & 0xFF, (word >> 16) & 0xFF]
                      ).astype(jnp.uint8)                  # (3, K/4, N)
    else:
        raise ValueError(f"bits must be 4, 6 or 8, got {bits}")
    return {"q": q, "scales": scales, "bits": bits,
            "group_size": group_size, "shape": (k, n)}


def _fp6_encode(x):
    """Nearest e3m2 code (sign + 3-bit exp, bias 3 + 2-bit mantissa) for
    |x| <= 28; arithmetic round-to-nearest (monotone codebook)."""
    ax = jnp.abs(x)
    # exponent of the nearest representable: normals span [0.25, 28]
    e = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(ax, 1e-12))) + 3, 0, 7
                 ).astype(jnp.int32)
    step = jnp.where(e == 0, 1.0 / 16.0, jnp.exp2(e.astype(jnp.float32) - 3) / 4)
    base = jnp.where(e == 0, 0.0, jnp.exp2(e.astype(jnp.float32) - 3))
    m = jnp.clip(jnp.round((ax - base) / step), 0, 3).astype(jnp.int32)
    # rounding up past m=3 bumps the exponent; re-derive via value compare
    v = base + m.astype(jnp.float32) * step
    nxt_e = jnp.minimum(e + 1, 7)
    nxt_v = jnp.exp2(nxt_e.astype(jnp.float32) - 3)
    bump = (jnp.abs(ax - nxt_v) < jnp.abs(ax - v)) & (e < 7)
    e = jnp.where(bump, nxt_e, e)
    m = jnp.where(bump, 0, m)
    code = (e << 2) | m
    return jnp.where(x < 0, code | 0x20, code)


def _fp6_decode_f32(code):
    """code int32 in [0, 63] → f32 value (vector arithmetic, no gather)."""
    sign = jnp.where((code & 0x20) != 0, -1.0, 1.0)
    e = ((code >> 2) & 0x7).astype(jnp.float32)
    m = (code & 0x3).astype(jnp.float32)
    mag = jnp.where(e == 0, m / 16.0, (1.0 + 0.25 * m) * jnp.exp2(e - 3.0))
    return sign * mag


# ---- the fused kernel ----------------------------------------------------

def _woq_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, bits, nk, out_dtype):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[0]                                       # (M, kt)
    s = s_ref[0]                                       # (1, nt) f32
    if bits == 8:
        w = q_ref[0].astype(jnp.float32)               # (kt, nt)
    elif bits == 4:
        u = q_ref[0].astype(jnp.int32) & 0xFF
        half = nk // 2
        nib = jnp.where(ki < half, u & 0xF, u >> 4)
        w = jnp.where(nib >= 8, nib - 16, nib).astype(jnp.float32)
    else:   # fp6: three byte planes → 6-bit code of this quarter
        b = q_ref[...].astype(jnp.int32) & 0xFF        # (3, kt, nt)
        word = b[0] | (b[1] << 8) | (b[2] << 16)
        quarter = nk // 4
        shift = 6 * (ki // quarter)
        code = (word >> shift) & 0x3F
        w = _fp6_decode_f32(code)
    w = (w * s).astype(x.dtype)
    acc_ref[...] += jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _done():
        o_ref[0] = acc_ref[...].astype(out_dtype)


def woq_matmul(x, qstate, *, block_n: int = 256):
    """y = x @ dequant(Wq): x (M, K) bf16/f32; returns (M, N) in x.dtype.

    The K-tile equals the quantization group size, so each k-step consumes
    exactly one scale row. M rides whole (decode batches are small); N is
    tiled by ``block_n``.
    """
    k, n = qstate["shape"]
    bits, g = qstate["bits"], qstate["group_size"]
    q, scales = qstate["q"], qstate["scales"]
    m = x.shape[0]
    assert x.shape[1] == k, (x.shape, qstate["shape"])
    nt = min(block_n, n)
    if n % nt:
        nt = n  # fall back to one tile when block_n doesn't divide N
    nk = k // g
    grid = (n // nt, nk)
    planes = {8: 1, 4: 2, 6: 4}[bits]
    kq = k // planes                                    # byte rows per plane

    def s_map(ni, ki):
        return (ki, 0, ni)

    if bits == 6:
        q3 = q.reshape(3, kq, n)
        q_spec = pl.BlockSpec((3, g, nt), lambda ni, ki: (0, ki % (kq // g), ni))
        q_in = q3
    else:
        q_spec = pl.BlockSpec((1, g, nt),
                              lambda ni, ki: (0, ki % (kq // g), ni))
        q_in = q.reshape(1, *q.shape)

    out = pl.pallas_call(
        functools.partial(_woq_kernel, bits=bits, nk=nk, out_dtype=x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, m, g), lambda ni, ki: (0, 0, ki)),
            q_spec,
            pl.BlockSpec((1, 1, nt), s_map),   # scales as (nk, 1, N): the
            # (1, nt) tail matches the array dims (TPU block tiling rule)
        ],
        out_specs=pl.BlockSpec((1, m, nt), lambda ni, ki: (0, 0, ni)),
        scratch_shapes=[pltpu.VMEM((m, nt), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((1, m, n), x.dtype),
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(x.reshape(1, m, k), q_in, scales.reshape(nk, 1, n))
    return out[0]


def woq_dequantize(qstate, dtype=jnp.bfloat16):
    """Full dequantization (reference/verification path)."""
    k, n = qstate["shape"]
    bits, g = qstate["bits"], qstate["group_size"]
    q, scales = qstate["q"], qstate["scales"]
    s_full = jnp.repeat(scales, g, axis=0)
    if bits == 8:
        w = q.astype(jnp.float32)
    elif bits == 4:
        u = q.astype(jnp.int32) & 0xFF
        lo = u & 0xF
        hi = u >> 4
        lo = jnp.where(lo >= 8, lo - 16, lo)
        hi = jnp.where(hi >= 8, hi - 16, hi)
        w = jnp.concatenate([lo, hi]).astype(jnp.float32)
    else:
        b = q.astype(jnp.int32) & 0xFF
        word = b[0] | (b[1] << 8) | (b[2] << 16)
        codes = [(word >> (6 * i)) & 0x3F for i in range(4)]
        w = jnp.concatenate([_fp6_decode_f32(c) for c in codes])
    return (w * s_full).astype(dtype)
