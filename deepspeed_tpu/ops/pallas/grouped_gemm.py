"""Grouped (expert) matmul for MoE.

Analog of ``inference/v2/kernels/cutlass_ops/moe_gemm`` (grouped GEMM over
per-expert token groups). On TPU the idiomatic primitive is
``jax.lax.ragged_dot`` (Megablox-style: rows grouped by expert, group sizes
ragged) which XLA lowers to MXU-tiled grouped matmul; a dense einsum fallback
covers platforms/shapes where ragged_dot is unavailable.
"""

import jax
import jax.numpy as jnp


def grouped_gemm(tokens, expert_weights, group_sizes):
    """tokens: (T, E) rows sorted by expert; expert_weights: (X, E, F);
    group_sizes: (X,) rows per expert. Returns (T, F)."""
    if hasattr(jax.lax, "ragged_dot"):
        try:
            return jax.lax.ragged_dot(tokens, expert_weights, group_sizes)
        except Exception:
            pass
    # fallback: dense one-hot dispatch (O(T·X·E·F) worst case, fused by XLA)
    t = tokens.shape[0]
    x = expert_weights.shape[0]
    bounds = jnp.cumsum(group_sizes)
    expert_of_row = jnp.sum(jnp.arange(t)[:, None] >= bounds[None, :], axis=1)  # (T,)
    w_per_row = expert_weights[expert_of_row]        # (T, E, F) gather
    return jnp.einsum("te,tef->tf", tokens, w_per_row)


def moe_expert_ffn(tokens, wi_gate, wi_up, wo, group_sizes):
    """SwiGLU expert FFN over grouped rows: (T, E) → (T, E)."""
    g = grouped_gemm(tokens, wi_gate, group_sizes)
    u = grouped_gemm(tokens, wi_up, group_sizes)
    h = jax.nn.silu(g) * u
    return grouped_gemm(h, wo, group_sizes)
