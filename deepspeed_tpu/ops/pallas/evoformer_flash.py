"""Pallas Evoformer (DS4Science) bias-flash attention.

Analog of the reference CUTLASS kernel
(``csrc/deepspeed4science/evoformer_attn/attention.cu``): AlphaFold-style
attention over (B, N, S, H, D) MSA activations with a per-row mask bias
(B, N, 1, 1, S) and a pairwise triangle bias (B, 1, H, S, S) folded into the
logits IN-KERNEL — the (B, N, H, S, S) logits tensor never exists in HBM,
which is the entire point at MSA scale.

Design split (the sparse-flash precedent in this repo): the FORWARD is the
fused Pallas kernel (the serving-critical path and the memory headline);
the BACKWARD recomputes through the query-chunked XLA formulation
(``ops/evoformer.py``), whose peak is O(chunk · S) per (row, head) — same
numerics, bounded memory, no hand-written 5-tensor kernel backward. The
reference kernel's dB1/dB2 outputs fall out of the recompute's autodiff.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _evo_fwd_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, *,
                    has_b1, has_b2, block_k):
    q = q_ref[0, 0]                                     # (Bq, D), pre-scaled
    sk = k_ref.shape[2]
    num_kv = sk // block_k
    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_b1:
            b1 = b1_ref[0, 0, 0, pl.ds(j * block_k, block_k)]      # (Bk,)
            s = s + b1[None, :].astype(jnp.float32)
        if has_b2:
            # this q-block's (Bq, Bk) tile of the pair bias
            b2 = b2_ref[0, 0, :, pl.ds(j * block_k, block_k)]
            s = s + b2.astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((q.shape[0],), NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)


def evoformer_flash_fwd(q, k, v, bias1, bias2, *, scale,
                        block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Fused forward. q/k/v: (B, N, H, S, D) head-major; bias1:
    (B, N, 1, 1, S) or None; bias2: (B, 1, H, S, S) or None.
    Returns (B, N, H, S, D) in q's dtype."""
    b, n, h, s, d = q.shape
    bn = b * n
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    qf = (q * jnp.asarray(scale, q.dtype)).reshape(bn, h, s, d)
    kf = k.reshape(bn, h, s, d)
    vf = v.reshape(bn, h, s, d)
    has_b1 = bias1 is not None
    has_b2 = bias2 is not None
    b1 = (bias1.reshape(bn, 1, 1, s) if has_b1
          else jnp.zeros((1, 1, 1, s), q.dtype))
    b2 = (bias2.reshape(b, h, s, s) if has_b2
          else jnp.zeros((1, 1, block_q, s), q.dtype))

    grid = (bn, h, s // block_q)
    out = pl.pallas_call(
        functools.partial(_evo_fwd_kernel, has_b1=has_b1, has_b2=has_b2,
                          block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, s),
                         (lambda bi, hi, qi: (bi, 0, 0, 0)) if has_b1
                         else (lambda bi, hi, qi: (0, 0, 0, 0))),
            pl.BlockSpec((1, 1, block_q, s),
                         (lambda bi, hi, qi: (bi // n, hi, qi, 0)) if has_b2
                         else (lambda bi, hi, qi: (0, 0, 0, 0))),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bn, h, s, d), q.dtype),
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf, b1, b2)
    return out.reshape(b, n, h, s, d)


def evoformer_flash_supported(s, d, block_q=DEFAULT_BLOCK_Q,
                              block_k=DEFAULT_BLOCK_K) -> bool:
    """Mosaic alignment, not just divisibility: S must be lane-aligned (the
    bias blocks' last dim and the kv rows) — s % min(block, s) alone is
    vacuously true for any s <= block and would admit 70-row blocks."""
    if s % 128 != 0 or d not in (64, 128, 256):
        return False
    bq, bk = min(block_q, s), min(block_k, s)
    return s % bq == 0 and s % bk == 0
