"""Pallas fused decode attention over a contiguous KV cache.

Analog of the reference's v1 fused decode kernel (``softmax_context`` in
``csrc/transformer/inference/csrc/`` — KV-cache attention for the
kernel-injection engine): one query token per sequence attends over its
(B, S_max, KVH, D) cache slice with online softmax in VMEM — the
(B, H, S_max) logits tensor the XLA path materializes never exists.

Structure matches ``paged_attention.py`` with the block table replaced by
contiguous block indexing; GQA runs each kv head's query group as rows of
one (G, D) tile. Grid = (batch, kv_head, cache_block); m/l/acc scratch
carried across the block dimension.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK = 512


def _decode_kernel(len_ref,                    # scalar prefetch
                   q_ref, k_ref, v_ref,        # blocks
                   o_ref,
                   m_ref, l_ref, acc_ref,
                   *, block, n_blocks, scale):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seq_len = len_ref[b]

    @pl.when(j * block < seq_len)
    def _block():
        q = q_ref[0, 0]                                   # (G, D)
        k = k_ref[0, 0]                                   # (block, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        slot = j * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(slot < seq_len, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def fused_decode_attention(q, k_cache, v_cache, cache_len, *, scale=None,
                           block=DEFAULT_BLOCK):
    """q: (B, H, D) single decode token per sequence; k_cache/v_cache:
    (B, S_max, KVH, D); cache_len: (B,) valid entries (including the one
    just written). Returns (B, H, D)."""
    b, h, d = q.shape
    s_max, kvh = k_cache.shape[1], k_cache.shape[2]
    block = min(block, s_max)
    if s_max % block:
        raise ValueError(f"S_max={s_max} not divisible by block={block}")
    n_blocks = s_max // block
    group = h // kvh
    scale = float(scale if scale is not None else d ** -0.5)

    qg = q.reshape(b, kvh, group, d)
    # (B, S, KVH, D) → (B, KVH, S, D) so the kernel reads (block, D) tiles
    km = k_cache.swapaxes(1, 2)
    vm = v_cache.swapaxes(1, 2)

    def q_map(bi, hi, ji, lens):
        return (bi, hi, 0, 0)

    def kv_map(bi, hi, ji, lens):
        return (bi, hi, ji, 0)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, block=block, n_blocks=n_blocks,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, kvh, n_blocks),
            in_specs=[
                pl.BlockSpec((1, 1, group, d), q_map),
                pl.BlockSpec((1, 1, block, d), kv_map),
                pl.BlockSpec((1, 1, block, d), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, group, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
        interpret=jax.default_backend() != "tpu",
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
    )(cache_len.astype(jnp.int32), qg, km, vm)
    return out.reshape(b, h, d)
