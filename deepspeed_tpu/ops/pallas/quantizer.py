"""Block quantization kernels (int8/int4).

Analog of the reference's ``csrc/quantization/`` (quantize.cu /
dequantize.cu / swizzled_quantize.cu): symmetric per-group quantization used
by ZeRO++ quantized-weight allgather (qwZ) and quantized-gradient reduction
(qgZ), and by ZeRO-Inference weight-only quantization.

The Pallas kernel fuses max-reduction, scale computation and rounding per
group; groups are rows of a (num_groups, group_size) view, matching the
reference's group layout. int4 packs two nibbles per int8 byte.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() != "tpu"


def _quant_kernel(x_ref, q_ref, scale_ref, *, qmax):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-10) / qmax
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    q_ref[:] = q.astype(jnp.int8)
    scale_ref[:] = scale


def quantize_int8(x, group_size: int = 256):
    """x: any shape with total % group_size == 0 →
    (q int8 same-shape, scales (groups, 1) fp32)."""
    orig_shape = x.shape
    flat = x.reshape(-1, group_size)
    g = flat.shape[0]
    block_g = min(g, 256)
    if g % block_g != 0:
        block_g = 1
    q, scale = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=127.0),
        grid=(g // block_g,),
        in_specs=[pl.BlockSpec((block_g, group_size), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((block_g, group_size), lambda i: (i, 0)),
                   pl.BlockSpec((block_g, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct(flat.shape, jnp.int8),
                   jax.ShapeDtypeStruct((g, 1), jnp.float32)],
        interpret=_interpret(),
    )(flat)
    return q.reshape(orig_shape), scale


def dequantize_int8(q, scales, orig_dtype=jnp.float32, group_size: int = 256):
    flat = q.reshape(-1, group_size)
    out = flat.astype(jnp.float32) * scales
    return out.reshape(q.shape).astype(orig_dtype)


def quantize_int4(x, group_size: int = 256):
    """Symmetric int4: values in [-7, 7], packed two per byte."""
    orig_shape = x.shape
    flat = x.reshape(-1, group_size)
    g = flat.shape[0]
    block_g = min(g, 256)
    if g % block_g != 0:
        block_g = 1
    q, scale = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=7.0),
        grid=(g // block_g,),
        in_specs=[pl.BlockSpec((block_g, group_size), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((block_g, group_size), lambda i: (i, 0)),
                   pl.BlockSpec((block_g, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct(flat.shape, jnp.int8),
                   jax.ShapeDtypeStruct((g, 1), jnp.float32)],
        interpret=_interpret(),
    )(flat)
    # pack pairs of nibbles: (..., 2k) | (..., 2k+1) << 4
    lo = (q[:, 0::2].astype(jnp.int32) & 0xF)
    hi = (q[:, 1::2].astype(jnp.int32) & 0xF) << 4
    packed = (lo | hi).astype(jnp.int8)
    return packed, scale, orig_shape


def dequantize_int4(packed, scales, orig_shape, orig_dtype=jnp.float32,
                    group_size: int = 256):
    p = packed.astype(jnp.int32)
    lo = (p & 0xF)
    hi = (p >> 4) & 0xF
    # sign-extend 4-bit two's complement
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    g = packed.shape[0]
    out = jnp.zeros((g, group_size), jnp.int32)
    out = out.at[:, 0::2].set(lo)
    out = out.at[:, 1::2].set(hi)
    return (out.astype(jnp.float32) * scales).reshape(orig_shape).astype(orig_dtype)


# Reference-named convenience wrappers (csrc/quantization/pt_binding.cpp
# exposes quantize/dequantize pairs per bit width)

def ds_quantize(x, groups: int, bits: int = 8):
    group_size = x.size // groups
    if bits == 8:
        return quantize_int8(x, group_size)
    if bits == 4:
        return quantize_int4(x, group_size)
    raise ValueError(f"unsupported bits={bits}")
