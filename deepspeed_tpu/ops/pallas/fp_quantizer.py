"""FP8 quantization with stochastic rounding.

Analog of ``csrc/fp_quantizer/fp_quantize.cu`` (FP8/FP6/FP12 quantize /
dequantize with stochastic rounding). TPU v5+ has native fp8 support
(e4m3/e5m2); the kernel computes per-group scales to use the fp8 dynamic
range and stochastically rounds with the on-core PRNG — gradient/weight
compression without bias.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def _interpret():
    return jax.default_backend() != "tpu"


def _fp8_quant_kernel(x_ref, seed_ref, q_ref, scale_ref, *, fmax, stochastic):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / fmax
    scaled = x / scale
    if stochastic:
        pltpu.prng_seed(seed_ref[0])
        bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
        q = pltpu.stochastic_round(scaled, bits, target_dtype=q_ref.dtype)
    else:
        q = scaled.astype(q_ref.dtype)
    q_ref[:] = q
    scale_ref[:] = scale


def quantize_fp8(x, group_size: int = 256, fmt: str = "e4m3", stochastic: bool = True,
                 seed: int = 0):
    """x → (q fp8, scales (groups, 1) fp32)."""
    dtype = jnp.float8_e4m3fn if fmt == "e4m3" else jnp.float8_e5m2
    fmax = E4M3_MAX if fmt == "e4m3" else E5M2_MAX
    orig_shape = x.shape
    flat = x.reshape(-1, group_size)
    g = flat.shape[0]
    if _interpret():
        # interpreter path: deterministic rounding (prng/stochastic_round are
        # TPU-core features); numerics identical up to rounding mode.
        absmax = jnp.max(jnp.abs(flat.astype(jnp.float32)), axis=-1, keepdims=True)
        scale = jnp.maximum(absmax, 1e-12) / fmax
        q = (flat / scale).astype(dtype)
        return q.reshape(orig_shape), scale
    block_g = min(g, 256)
    if g % block_g != 0:
        block_g = 1
    q, scale = pl.pallas_call(
        functools.partial(_fp8_quant_kernel, fmax=fmax, stochastic=stochastic),
        grid=(g // block_g,),
        in_specs=[pl.BlockSpec((block_g, group_size), lambda i: (i, 0)),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec((block_g, group_size), lambda i: (i, 0)),
                   pl.BlockSpec((block_g, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct(flat.shape, dtype),
                   jax.ShapeDtypeStruct((g, 1), jnp.float32)],
        interpret=False,
    )(flat, jnp.asarray([seed], jnp.int32))
    return q.reshape(orig_shape), scale


def dequantize_fp8(q, scales, orig_dtype=jnp.float32, group_size: int = 256):
    flat = q.reshape(-1, group_size).astype(jnp.float32)
    return (flat * scales).reshape(q.shape).astype(orig_dtype)
