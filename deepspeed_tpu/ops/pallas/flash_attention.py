"""Pallas flash attention (training) for TPU.

Replaces the reference's CUDA fused-attention kernels
(``csrc/transformer/inference/csrc/softmax_context`` and the training
transformer kernel, SURVEY.md §2.2): FlashAttention-2-style online-softmax
tiling sized for the MXU, fp32 accumulation, causal block skipping, GQA via
block index maps (kv heads are never materialized per-q-head in HBM).

Layout inside the kernel: (B, H, S, D). The public wrapper takes the model's
(B, S, H, D) and transposes (free under XLA fusion).
"""

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _interpret() -> bool:
    # Mosaic compiles only on TPU; anywhere else run the kernel interpreted
    # (slow but exact) so tests exercise the same code path.
    return jax.default_backend() != "tpu"


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------

def _q_block_ranges(qi, block_q, block_k, num_kv, causal, window):
    """KV-block loop bounds for q block qi: (kv_lo, full_lo, full_hi, kv_hi).

    [kv_lo, full_lo) and [full_hi, kv_hi) run with masking; [full_lo,
    full_hi) is mask-free. A sliding window both LOWERS kv_hi's
    counterpart kv_lo (blocks left of every row's window are skipped —
    the flash win for long-context Mistral) and shrinks the mask-free
    middle from below.
    """
    if causal:
        kv_hi = jax.lax.min((((qi + 1) * block_q + block_k - 1) // block_k), num_kv)
        n_full = (qi * block_q) // block_k
    else:
        kv_hi = num_kv
        n_full = num_kv
    if window is None:
        return 0, 0, n_full, kv_hi
    # first block holding any col visible to the block's first row
    kv_lo = jax.lax.max(0, (qi * block_q - window + 1) // block_k)
    # first block whose cols are inside the window of even the LAST row
    lo_full = jax.lax.max(0, ((qi + 1) * block_q - window + block_k - 1) // block_k)
    full_lo = jax.lax.clamp(kv_lo, lo_full, kv_hi)
    full_hi = jax.lax.clamp(full_lo, n_full, kv_hi)
    return kv_lo, full_lo, full_hi, kv_hi


def _fwd_kernel(q_ref, k_ref, v_ref, slopes_ref, seg_ref, o_ref, lse_ref, *, causal,
                alibi, segmented, window, block_q, block_k):
    qi = pl.program_id(2)
    q = q_ref[0, 0]                                      # (Bq, D) input dtype
    seq_k = k_ref.shape[2]
    num_kv = seq_k // block_k
    slope = slopes_ref[pl.program_id(1), 0] if alibi else None
    qseg = seg_ref[0, 0, pl.ds(pl.multiple_of(qi * block_q, block_q), block_q)] \
        if segmented else None
    kv_lo, full_lo, full_hi, kv_hi = _q_block_ranges(
        qi, block_q, block_k, num_kv, causal, window)
    if segmented:
        full_lo, full_hi = kv_lo, kv_lo   # every block needs the seg mask

    def make_body(masked):
        def body(j, carry):
            m, l, acc = carry
            k = k_ref[0, 0, pl.ds(pl.multiple_of(j * block_k, block_k), block_k), :]                   # (Bk, D)
            v = v_ref[0, 0, pl.ds(pl.multiple_of(j * block_k, block_k), block_k), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)           # (Bq, Bk)
            if alibi or masked:
                rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if alibi:   # in-kernel ALiBi: no (H, S, S) bias ever touches HBM
                s = s + slope * (cols - rows).astype(jnp.float32)
            if masked:
                keep = rows >= cols if causal else \
                    jnp.ones(s.shape, jnp.bool_)
                if window is not None:
                    keep = keep & (rows - cols < window)
                if segmented:   # packed sequences: attend within segment only
                    kseg = seg_ref[0, 0, pl.ds(pl.multiple_of(j * block_k, block_k),
                                               block_k)]
                    keep = keep & (qseg[:, None] == kseg[None, :])
                s = jnp.where(keep, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_new = l * alpha + jnp.sum(p, axis=1)
            acc_new = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    carry = jax.lax.fori_loop(kv_lo, full_lo, make_body(True),
                              (m0, l0, acc0))
    carry = jax.lax.fori_loop(full_lo, full_hi, make_body(False), carry)
    m, l, acc = jax.lax.fori_loop(full_hi, kv_hi, make_body(True), carry)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, 0] = m + jnp.log(l_safe)


def _fwd(q, k, v, slopes, seg, causal, alibi, segmented, window, block_q, block_k):
    b, h, sq, d = q.shape
    kvh = k.shape[1]
    grid = (b, h, sq // block_q)
    group = h // kvh

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, alibi=alibi,
                          segmented=segmented, window=window,
                          block_q=block_q, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, k.shape[2], d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, k.shape[2], d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((q.shape[1], 128), lambda bi, hi, qi: (0, 0)),
            pl.BlockSpec((1, 1, seg.shape[2]), lambda bi, hi, qi: (bi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(q, k, v, slopes, seg)
    return out, lse


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, slopes_ref, seg_ref,
               dq_ref, *, causal, alibi, segmented, window, block_q, block_k):
    qi = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, 0]
    delta = delta_ref[0, 0, 0]
    slope = slopes_ref[pl.program_id(1), 0] if alibi else None
    qseg = seg_ref[0, 0, pl.ds(pl.multiple_of(qi * block_q, block_q), block_q)] \
        if segmented else None
    seq_k = k_ref.shape[2]
    num_kv = seq_k // block_k
    kv_lo, full_lo, full_hi, kv_hi = _q_block_ranges(
        qi, block_q, block_k, num_kv, causal, window)
    if segmented:
        full_lo, full_hi = kv_lo, kv_lo

    def make_body(masked):
        def body(j, dq):
            k = k_ref[0, 0, pl.ds(pl.multiple_of(j * block_k, block_k), block_k), :]
            v = v_ref[0, 0, pl.ds(pl.multiple_of(j * block_k, block_k), block_k), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if alibi or masked:
                rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if alibi:
                s = s + slope * (cols - rows).astype(jnp.float32)
            if masked:
                keep = rows >= cols if causal else jnp.ones(s.shape, jnp.bool_)
                if window is not None:
                    keep = keep & (rows - cols < window)
                if segmented:
                    kseg = seg_ref[0, 0, pl.ds(pl.multiple_of(j * block_k, block_k),
                                               block_k)]
                    keep = keep & (qseg[:, None] == kseg[None, :])
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])                                   # (Bq, Bk)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, None])).astype(k.dtype)
            return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                            preferred_element_type=jnp.float32)
        return body

    dq = jax.lax.fori_loop(kv_lo, full_lo, make_body(True),
                           jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq = jax.lax.fori_loop(full_lo, full_hi, make_body(False), dq)
    dq = jax.lax.fori_loop(full_hi, kv_hi, make_body(True), dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, slopes_ref, seg_ref,
                dk_ref, dv_ref, *, causal, alibi, segmented, window, block_q, block_k):
    ki = pl.program_id(2)
    k = k_ref[0, 0]                                       # (Bk, D)
    v = v_ref[0, 0]
    slope = slopes_ref[pl.program_id(1), 0] if alibi else None
    kseg = seg_ref[0, 0, pl.ds(pl.multiple_of(ki * block_k, block_k), block_k)] \
        if segmented else None
    seq_q = q_ref.shape[2]
    num_q = seq_q // block_q
    if causal:
        q_lo = (ki * block_k) // block_q
        # q blocks at/above i_um sit fully below the diagonal: no masking
        i_um = ((ki + 1) * block_k - 1 + block_q - 1) // block_q
    else:
        q_lo = 0
        i_um = 0
    if window is not None:
        # dual of _q_block_ranges: rows past the window of the block's last
        # col contribute nothing (r < c + window); the mask-free middle ends
        # once the block's LAST row leaves the window of the first col
        q_hi_w = jax.lax.min(num_q,
                             ((ki + 1) * block_k - 1 + window + block_q - 1) // block_q)
        i_full_end = jax.lax.max(q_lo, (ki * block_k + window) // block_q)
    else:
        q_hi_w = num_q
        i_full_end = num_q

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[0, 0, pl.ds(pl.multiple_of(i * block_q, block_q), block_q), :]
            do = do_ref[0, 0, pl.ds(pl.multiple_of(i * block_q, block_q), block_q), :]
            lse = lse_ref[0, 0, 0, pl.ds(pl.multiple_of(i * block_q, block_q), block_q)]
            delta = delta_ref[0, 0, 0, pl.ds(pl.multiple_of(i * block_q, block_q), block_q)]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)          # (Bq, Bk)
            if alibi or masked:
                rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if alibi:
                s = s + slope * (cols - rows).astype(jnp.float32)
            if masked:
                keep = rows >= cols if causal else jnp.ones(s.shape, jnp.bool_)
                if window is not None:
                    keep = keep & (rows - cols < window)
                if segmented:
                    qseg = seg_ref[0, 0, pl.ds(pl.multiple_of(i * block_q, block_q),
                                               block_q)]
                    keep = keep & (qseg[:, None] == kseg[None, :])
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dv_new = dv + jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                                              preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, None])).astype(q.dtype)
            dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                              preferred_element_type=jnp.float32)
            return dk_new, dv_new
        return body

    zeros = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    if segmented:   # every q block needs the segment mask
        m1_end = q_hi_w
        full_end = q_hi_w
    else:
        m1_end = jax.lax.clamp(q_lo, jax.lax.min(i_um, num_q) if causal else 0, q_hi_w)
        full_end = jax.lax.clamp(m1_end, i_full_end, q_hi_w)
    dk, dv = jax.lax.fori_loop(q_lo, m1_end, make_body(True), (zeros, zeros))
    dk, dv = jax.lax.fori_loop(m1_end, full_end, make_body(False), (dk, dv))
    dk, dv = jax.lax.fori_loop(full_end, q_hi_w, make_body(True), (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(causal, alibi, segmented, window, block_q, block_k, residuals, g):
    q, k, v, slopes, seg, out, lse = residuals
    b, h, sq, d = q.shape
    kvh = k.shape[1]
    group = h // kvh
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]  # (B,H,1,Sq)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, alibi=alibi,
                          segmented=segmented, window=window,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, k.shape[2], d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, k.shape[2], d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
            pl.BlockSpec((1, 1, 1, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
            pl.BlockSpec((q.shape[1], 128), lambda bi, hi, qi: (0, 0)),
            pl.BlockSpec((1, 1, seg.shape[2]), lambda bi, hi, qi: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(q, k, v, do, lse, delta, slopes, seg)

    sk = k.shape[2]
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, alibi=alibi,
                          segmented=segmented, window=window,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki_: (bi, hi // group, ki_, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki_: (bi, hi // group, ki_, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, sq), lambda bi, hi, ki_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, sq), lambda bi, hi, ki_: (bi, hi, 0, 0)),
            pl.BlockSpec((q.shape[1], 128), lambda bi, hi, ki_: (0, 0)),
            pl.BlockSpec((1, 1, seg.shape[2]), lambda bi, hi, ki_: (bi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki_: (bi, hi, ki_, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki_: (bi, hi, ki_, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), q.dtype),
        ],
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(q, k, v, do, lse, delta, slopes, seg)

    if group > 1:
        dk = dk_h.reshape(b, kvh, group, sk, d).sum(axis=2).astype(k.dtype)
        dv = dv_h.reshape(b, kvh, group, sk, d).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h.astype(k.dtype), dv_h.astype(v.dtype)
    return dq, dk, dv, jnp.zeros_like(slopes), \
        np.zeros(seg.shape, jax.dtypes.float0)


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_bhsd(q, k, v, slopes, seg, causal, alibi, segmented, window, block_q, block_k):
    """Scale-free core: callers fold the softmax scale into q.

    ``slopes``: (H, 128) fp32 per-head ALiBi slopes (lane-broadcast; a
    zeros placeholder when ``alibi`` is False)."""
    out, _ = _fwd(q, k, v, slopes, seg, causal, alibi, segmented, window,
                  block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, slopes, seg, causal, alibi, segmented, window,
                    block_q, block_k):
    out, lse = _fwd(q, k, v, slopes, seg, causal, alibi, segmented, window,
                    block_q, block_k)
    return out, (q, k, v, slopes, seg, out, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, *, causal=True, segment_ids=None, scale=None,
                    alibi_slopes=None, window=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """q: (B, S, H, D); k/v: (B, S, KVH, D) → (B, S, H, D).

    Requires S % block == 0 and D in {64, 128, 256}; callers
    (``ops/attention.py``) fall back to the XLA path otherwise.
    ``alibi_slopes``: (H,) per-head slopes — the bias slope*(k-q) is
    computed inside the kernel from block coordinates (no O(S^2) bias in
    HBM), fwd and bwd. Slopes are NON-DIFFERENTIABLE here (the vjp
    returns zero for them): ALiBi slopes are fixed constants, not
    trainable parameters.
    """
    if window is not None:
        if not causal:
            raise NotImplementedError("flash sliding window is causal-only")
        if not isinstance(window, int) or window <= 0:
            raise ValueError("flash window must be a static positive int")
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q != 0 or s % block_k != 0:
        raise ValueError(f"seq len {s} not divisible by blocks ({block_q},{block_k})")
    scale = scale if scale is not None else d ** -0.5
    segmented = segment_ids is not None
    if segmented:
        seg = jnp.asarray(segment_ids, jnp.int32)[:, None, :]   # (B, 1, S)
    else:
        seg = jnp.zeros((b, 1, 128), jnp.int32)
    alibi = alibi_slopes is not None
    if alibi:
        slopes = jnp.broadcast_to(
            jnp.asarray(alibi_slopes, jnp.float32)[:, None], (h, 128))
    else:
        slopes = jnp.zeros((h, 128), jnp.float32)
    # Fold the softmax scale into q outside the custom_vjp: the kernels run
    # scale-free (one fewer VPU pass over every (Bq, Bk) score tile, fwd and
    # bwd) and autodiff chains d(q*scale)/dq for free.
    qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_bhsd(qt, kt, vt, slopes, seg, bool(causal), alibi, segmented,
                      window, int(block_q), int(block_k))
    return out.transpose(0, 2, 1, 3)
