"""Ring attention op alias — implementation lives with the sequence-parallel
layer (``deepspeed_tpu/sequence/ring_attention.py``); re-exported here so the
op-builder registry resolves it like the other kernels."""

from ...sequence.ring_attention import ring_attention  # noqa: F401
