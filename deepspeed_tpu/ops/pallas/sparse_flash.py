"""Block-sparse flash attention — the splash-kernel analog.

Analog of the reference's block-sparse attention kernels
(``deepspeed/ops/sparse_attention/`` Triton matmul/softmax over a block
layout; ``csrc/sparse_attention/utils.cpp``): attention cost scales with
the number of ACTIVE blocks, not S². The sparsity layout (a boolean
(S/block, S/block) grid from ``SparsityConfig.make_layout``) is compiled,
per kernel query tile, into

- a scalar-prefetched table of active key tiles + counts, so the Pallas
  grid only DMAs and computes live tiles (``pl.when`` retires padding
  slots), and
- precomputed per-tile token masks (causality folded in), applied inside
  the kernel for exact parity with the dense masked form.

Forward kernel only: the custom_vjp backward recomputes the dense masked
attention (correct, O(S²) — the reference trains BERT-era models where
that is acceptable; the fwd kernel is the inference/latency win).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
TILE_Q = 128
TILE_K = 128


def compile_layout_tables(layout: np.ndarray, layout_block: int,
                          causal: bool):
    """Coarsen the fine (n, n) layout to kernel tiles.

    Returns (table (QT, MA) int32 — active key tiles per query tile, padded;
    counts (QT,) int32; masks (QT, MA, TILE_Q, TILE_K) f32 0/1 — exact token
    mask per live tile with causality folded in)."""
    n = layout.shape[0]
    s = n * layout_block
    if s % TILE_Q or s % TILE_K:
        raise ValueError(f"seq {s} not divisible by kernel tiles")
    token = np.repeat(np.repeat(layout.astype(bool), layout_block, 0),
                      layout_block, 1)
    if causal:
        token &= np.tril(np.ones((s, s), bool))
    qt, kt = s // TILE_Q, s // TILE_K
    tiled = token.reshape(qt, TILE_Q, kt, TILE_K).transpose(0, 2, 1, 3)
    coarse = tiled.any(axis=(2, 3))                 # (QT, KT)
    counts = coarse.sum(axis=1).astype(np.int32)
    ma = max(1, int(counts.max()))
    table = np.zeros((qt, ma), np.int32)
    masks = np.zeros((qt, ma, TILE_Q, TILE_K), np.float32)
    for i in range(qt):
        active = np.nonzero(coarse[i])[0]
        table[i, :len(active)] = active
        for j, ki in enumerate(active):
            masks[i, j] = tiled[i, ki]
    return table, counts, masks


def _kernel(table_ref, counts_ref,                  # scalar prefetch
            q_ref, k_ref, v_ref, mask_ref, o_ref,
            m_ref, l_ref, acc_ref,
            *, max_active, scale):
    qi = pl.program_id(2)
    ji = pl.program_id(3)

    @pl.when(ji == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(ji < counts_ref[qi])
    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask_ref[0, 0] > 0, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(ji == max_active - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _dense_reference(q, k, v, token_mask, scale):
    """Dense masked attention over (B, H, S, D) — the backward-pass form."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(token_mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


class _LayoutCache:
    """layout bytes → compiled (table, counts, masks, token_mask)."""

    def __init__(self):
        self._store = {}

    def get(self, layout: np.ndarray, layout_block: int, causal: bool):
        key = (layout.tobytes(), layout.shape, layout_block, causal)
        if key not in self._store:
            table, counts, masks = compile_layout_tables(layout, layout_block,
                                                         causal)
            token = np.repeat(np.repeat(layout.astype(bool), layout_block, 0),
                              layout_block, 1)
            if causal:
                token &= np.tril(np.ones(token.shape, bool))
            self._store[key] = (table, counts, masks, token)
        return self._store[key]


_LAYOUTS = _LayoutCache()


def _fwd_kernel_call(qb, kb, vb, table, counts, masks, *, ma, scale):
    """Tables/masks are RUNTIME arguments (device arrays), not closure
    constants — baked constants blow past compile-payload limits at long S."""
    b, h, s, d = qb.shape
    qt = masks.shape[0]
    return pl.pallas_call(
        functools.partial(_kernel, max_active=ma, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, qt, ma),
            in_specs=[
                pl.BlockSpec((1, 1, TILE_Q, d),
                             lambda bi, hi, qi, ji, t, c: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, TILE_K, d),
                             lambda bi, hi, qi, ji, t, c: (bi, hi, t[qi, ji], 0)),
                pl.BlockSpec((1, 1, TILE_K, d),
                             lambda bi, hi, qi, ji, t, c: (bi, hi, t[qi, ji], 0)),
                pl.BlockSpec((1, 1, TILE_Q, TILE_K),
                             lambda bi, hi, qi, ji, t, c: (qi, ji, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, TILE_Q, d),
                                   lambda bi, hi, qi, ji, t, c: (bi, hi, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((TILE_Q, 1), jnp.float32),
                pltpu.VMEM((TILE_Q, 1), jnp.float32),
                pltpu.VMEM((TILE_Q, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, qt * TILE_Q, d), qb.dtype),
        interpret=jax.default_backend() != "tpu",
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
    )(table, counts, qb, kb, vb, masks)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _sparse_attn(qb, kb, vb, table, counts, masks, ma, scale, layout_block):
    return _fwd_kernel_call(qb, kb, vb, table, counts, masks, ma=ma, scale=scale)


def _sparse_attn_fwd(qb, kb, vb, table, counts, masks, ma, scale, layout_block):
    out = _sparse_attn(qb, kb, vb, table, counts, masks, ma, scale, layout_block)
    return out, (qb, kb, vb, masks, table, counts)


def _sparse_attn_bwd(ma, scale, layout_block, res, g):
    qb, kb, vb, masks, table, counts = res
    qt = masks.shape[0]
    s = qt * TILE_Q
    # reassemble the (S, S) token mask from the per-tile masks (in-graph, so
    # no giant constant rides the executable)
    full = jnp.zeros((qt, s // TILE_K, TILE_Q, TILE_K), jnp.float32)
    ji = jnp.arange(ma)
    valid = ji[None, :] < counts[:, None]                      # (QT, MA)
    qidx = jnp.broadcast_to(jnp.arange(qt)[:, None], (qt, ma)).reshape(-1)
    kidx = table.reshape(-1)
    contrib = jnp.where(valid.reshape(-1)[:, None, None], masks.reshape(-1, TILE_Q, TILE_K), 0.0)
    full = full.at[qidx, kidx].add(contrib)
    token_mask = full.transpose(0, 2, 1, 3).reshape(s, s) > 0

    def f(q_, k_, v_):
        return _dense_reference(q_, k_, v_, token_mask, scale)

    _, vjp = jax.vjp(f, qb, kb, vb)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None, None


_sparse_attn.defvjp(_sparse_attn_fwd, _sparse_attn_bwd)


def precompile_layout(layout, layout_block: int, causal: bool = False):
    """Host-side layout compilation: returns (table, counts, masks) device
    arrays to pass to ``sparse_flash_attention(..., tables=...)`` when the
    call sits inside an outer jit — passing them as runtime arguments keeps
    multi-MB mask tensors out of the compile payload."""
    table, counts, masks, _ = _LAYOUTS.get(np.asarray(layout, bool),
                                           layout_block, causal)
    return (jnp.asarray(table), jnp.asarray(counts),
            jnp.asarray(masks))


def sparse_flash_attention(q, k, v, layout=None, *, layout_block: int,
                           scale=None, causal: bool = False, tables=None):
    """Block-sparse attention with a block-skipping fwd kernel.

    q/k/v: (B, S, H, D); layout: (S/layout_block,)² bool numpy array — or
    pass ``tables=precompile_layout(...)`` (required under an outer jit).
    GQA repeats KV heads. Sequences shorter than one kernel tile fall back
    to the dense masked form.
    """
    b, s, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        k = jnp.repeat(k, h // kvh, axis=2)
        v = jnp.repeat(v, h // kvh, axis=2)
    scale = float(scale if scale is not None else d ** -0.5)
    qb = jnp.swapaxes(q, 1, 2)
    kb = jnp.swapaxes(k, 1, 2)
    vb = jnp.swapaxes(v, 1, 2)
    if tables is None:
        layout = np.asarray(layout, bool)
        if s % TILE_Q or s < TILE_Q:
            token = np.repeat(np.repeat(layout, layout_block, 0),
                              layout_block, 1)
            if causal:
                token &= np.tril(np.ones((s, s), bool))
            out = _dense_reference(qb, kb, vb, jnp.asarray(token), scale)
            return jnp.swapaxes(out, 1, 2)
        tables = precompile_layout(layout, layout_block, causal)
    table, counts, masks = tables
    ma = table.shape[1]
    out = _sparse_attn(qb, kb, vb, table, counts, masks, ma, scale,
                       layout_block)
    return jnp.swapaxes(out, 1, 2)
