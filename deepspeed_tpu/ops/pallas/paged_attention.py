"""Pallas paged decode attention: flash attention over in-place KV pages.

Analog of the reference's blocked-flash ragged kernel
(``inference/v2/kernels/ragged_ops/blocked_flash/flash.h``): each sequence's
KV lives scattered across fixed-size pages of a global pool; attention reads
the pages IN PLACE via the block table — the (B, S_max, KVH, D) gathered
cache the XLA fallback materializes never exists.

TPU mapping: the block table and sequence lengths are scalar-prefetched
(``pltpu.PrefetchScalarGridSpec``) so the kernel's BlockSpec index_map can
chase page indices while the pipeline double-buffers page fetches. Grid =
(batch, kv_head, page); online-softmax state (m, l, acc) lives in VMEM
scratch carried across the page dimension of the grid. GQA runs the q-head
group of each kv head as rows of one (G, D) tile.

Decode-only (one query token per sequence); prefill chunks use the XLA
path in ``inference/v2/model_runner.py`` where the gather amortizes over
the chunk's matmuls.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(bt_ref, len_ref,            # scalar prefetch
                   q_ref, k_ref, v_ref,        # blocks
                   o_ref,                      # output
                   m_ref, l_ref, acc_ref,      # VMEM scratch
                   *, page_size, pages_max, scale):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seq_len = len_ref[b]

    @pl.when(j * page_size < seq_len)
    def _page():
        q = q_ref[0, 0]                                   # (G, D)
        k = k_ref[0, 0]                                   # (bs, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)   # (G, bs)
        if scale != 1.0:
            s = s * scale
        slot = j * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(slot < seq_len, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(j == pages_max - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, kpool, vpool, block_tables, seq_lens, *, scale=None):
    """q: (B, H, D); kpool/vpool: (KVH, NB, bs, D) kv-head-major page pools;
    block_tables: (B, MB) int32 page ids per sequence (in order);
    seq_lens: (B,) int32 tokens currently in each sequence (incl. the one
    being decoded). Returns (B, H, D)."""
    b, h, d = q.shape
    kvh, nb, page_size, _ = kpool.shape
    mb = block_tables.shape[1]
    group = h // kvh
    scale = float(scale if scale is not None else d ** -0.5)

    # (B, H, D) → (B, KVH, G, D): one grid cell per (batch, kv head)
    qg = q.reshape(b, kvh, group, d)
    kp, vp = kpool, vpool

    grid = (b, kvh, mb)

    def q_map(bi, hi, ji, bt, lens):
        return (bi, hi, 0, 0)

    def kv_map(bi, hi, ji, bt, lens):
        return (hi, bt[bi, ji], 0, 0)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size, pages_max=mb,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, group, d), q_map),
                pl.BlockSpec((1, 1, page_size, d), kv_map),
                pl.BlockSpec((1, 1, page_size, d), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, group, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
        interpret=jax.default_backend() != "tpu",
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
    )(block_tables, seq_lens, qg, kp, vp)
    return out.reshape(b, h, d)
