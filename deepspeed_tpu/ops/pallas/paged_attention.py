"""Pallas paged attention: flash attention over in-place KV pages.

Analog of the reference's blocked-flash ragged kernel
(``inference/v2/kernels/ragged_ops/blocked_flash/flash.h``): each sequence's
KV lives scattered across fixed-size pages of a global pool; attention reads
the pages IN PLACE via the block table — the (B, S_max, KVH, D) gathered
cache the XLA fallback materializes never exists.

TPU mapping: the block table and per-sequence page bounds are
scalar-prefetched (``pltpu.PrefetchScalarGridSpec``) so the kernel's
BlockSpec index_map can chase page indices while the pipeline
double-buffers page fetches. Grid = (batch, kv_head, page); online-softmax
state (m, l, acc) lives in VMEM scratch carried across the page dimension.
GQA runs the q-head group of each kv head as rows of one tile.

One kernel covers BOTH decode (C == 1) and chunked prefill (C > 1) — the
Dynamic-SplitFuse unification: queries are rows of a (C*G, D) tile whose
per-row absolute positions ride in as an f32 block, so per-row causal
masking, sliding windows, and ALiBi (reference blocked-flash handles these
in-kernel too) need no gathered bias tensors. Pages wholly outside
[min_pos - window, max_pos] are skipped by the grid predicate.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_kernel(lyr_ref, bt_ref, cs_ref, lo_ref, win_ref,   # scalar prefetch
                  q_ref, *rest,                   # K k-pages, K v-pages, ...
                  page_size, grid_steps, pages_per_step, scale, softcap,
                  use_alibi):
    K = pages_per_step
    k_refs = rest[0:K]
    v_refs = rest[K:2 * K]
    (pos_ref, slope_ref, ck_ref, cv_ref, cpos_ref,   # chunk KV blocks
     o_ref,                                          # output
     m_ref, l_ref, acc_ref) = rest[2 * K:]           # VMEM scratch
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    win = win_ref[0]          # runtime: 0/negative = global (per-layer
    # window patterns arrive as traced scan elements, so the window cannot
    # be a compile-time constant)
    pos = pos_ref[0, 0].reshape(-1, 1)                    # (R, 1) f32
    wf = win.astype(jnp.float32)

    def online_update(s, mask, v):
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    def scores(q, k, key_pos):
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if scale != 1.0:
            s = s * scale
        if use_alibi:
            # slope block is already this kv-head's (1, 1, R) slice
            s = s + slope_ref[0, 0].reshape(-1, 1) * (key_pos - pos)
        if softcap:
            s = softcap * jnp.tanh(s / softcap)
        mask = key_pos <= pos
        mask = jnp.logical_and(mask,
                               jnp.logical_or(win <= 0, key_pos > pos - wf))
        return s, mask

    # pool slots >= cs (the current chunk's first position) are stale: the
    # chunk's own KV arrives as separate blocks below, NOT via the pool —
    # keeping the pool read-only inside the layer scan is what lets XLA
    # leave it in place (a scattered-then-read pool forces pool-sized
    # defensive copies; measured pool-size-bound decode).
    # One grid step covers K pages fused into ONE (R, K*bs) score matmul —
    # per-step overhead (DMA latency, semaphores) amortizes over K pages and
    # the MXU tile is K× wider (one-page steps measurably lose to the XLA
    # gather path on latency-floored parts; VERDICT r4).
    active = jnp.logical_and(j * K * page_size < cs_ref[b],
                             (j * K + K) * page_size > lo_ref[b])

    @pl.when(active)
    def _pages():
        q = q_ref[0, 0]                                   # (R, D) R = C*G
        k = jnp.concatenate([r[0, 0, 0] for r in k_refs], axis=0)  # (K*bs, D)
        v = jnp.concatenate([r[0, 0, 0] for r in v_refs], axis=0)
        # logical slot of each fetched key: pages past the table's end are
        # fetched clamped but their logical slots are >= MB*bs >= cs → the
        # staleness mask kills them
        slot = (j * K * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], K * page_size), 1)).astype(jnp.float32)
        s, mask = scores(q, k, slot)
        mask = jnp.logical_and(mask, slot < cs_ref[b].astype(jnp.float32))
        online_update(s, mask, v)

    @pl.when(j == grid_steps - 1)
    def _chunk_and_finalize():
        q = q_ref[0, 0]
        ck = ck_ref[0, 0]                                 # (C, D)
        cv = cv_ref[0, 0]
        kpos = cpos_ref[0, 0].reshape(1, -1)              # (1, C) f32; -1 pad
        s, mask = scores(q, ck, kpos)
        mask = jnp.logical_and(mask, kpos >= 0)           # pad keys dead
        online_update(s, mask, cv)
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_ragged_attention(q, kpool, vpool, block_tables, positions,
                           chunk_k=None, chunk_v=None, *, layer=None,
                           scale=None, window=0, alibi_slopes=None,
                           softcap=0.0, pages_per_step=None):
    """Unified paged attention for decode AND chunked prefill.

    q: (B, C, H, D) — C query tokens per sequence (1 = decode);
    kpool/vpool: the FULL (L, KVH, NB, bs, D) kv-head-major page pools with
    ``layer`` the (traced) layer index — the kernel's BlockSpec chases
    (layer, head, page) directly, so no per-layer pool slice is ever
    materialized. A 4-D (KVH, NB, bs, D) single-layer pool with
    ``layer=None`` is also accepted. The pools are READ-ONLY here and must
    NOT yet contain the current chunk: ``chunk_k``/``chunk_v`` (B, C, KVH,
    D) carry the chunk's own KV, processed as a final virtual page with
    per-key positions = ``positions`` (pool slots >= the chunk's first
    position are treated as stale and masked). This keeps the pool
    loop-invariant across the layer scan — the caller scatters all layers'
    chunk KV in one token-sized update afterwards. With ``chunk_k=None``
    the pool is taken as ALREADY containing every slot up to each query's
    position (the pre-round-4 contract, kept for the v1 fused-decode path).

    block_tables: (B, MB) int32 page ids; positions: (B, C)
    int32 absolute slot of each query, -1 for padding rows (their outputs
    are garbage the caller discards). Query at slot p attends slots <= p,
    within (p - window, p] when ``window`` > 0; ``alibi_slopes``: (H,)
    per-head slopes applied in-kernel; ``softcap``: Gemma-2 attention-logit
    tanh cap. Returns (B, C, H, D).
    """
    if kpool.ndim == 4:
        kpool = kpool[None]
        vpool = vpool[None]
        layer = 0
    b, c, h, d = q.shape
    _, kvh, nb, page_size, _ = kpool.shape
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)
    mb = block_tables.shape[1]
    group = h // kvh
    rows = c * group
    scale = float(scale if scale is not None else d ** -0.5)
    if window is None:
        window = 0
    softcap = float(softcap or 0.0)

    # (B, C, H, D) → (B, KVH, C*G, D): row r = c*G + g
    qg = q.reshape(b, c, kvh, group, d).transpose(0, 2, 1, 3, 4).reshape(
        b, kvh, rows, d)
    # per-row positions (B, 1, C*G) as f32 (exact to 2^24; int blocks are
    # fragile on the tunneled Mosaic compiler — see verify skill notes)
    pos_rep = jnp.repeat(positions, group, axis=1).astype(jnp.float32)
    pos_rep = pos_rep.reshape(b, 1, rows)
    valid = positions >= 0
    win_arr = jnp.asarray(window, jnp.int32).reshape(1)
    minpos = jnp.min(jnp.where(valid, positions, 1 << 30), axis=1)
    if chunk_k is not None:
        # chunk KV → (B, KVH, C, D) blocks + (B, 1, C) f32 key positions;
        # pool is valid only BELOW the chunk's first position
        ckg = chunk_k.astype(q.dtype).transpose(0, 2, 1, 3)
        cvg = chunk_v.astype(q.dtype).transpose(0, 2, 1, 3)
        cpos = positions.astype(jnp.float32).reshape(b, 1, c)
        # fully-padded rows have no valid positions: zero pages, not 2^30
        chunk_start = jnp.where(minpos == 1 << 30, 0, minpos).astype(jnp.int32)
    else:
        # pool already holds every slot <= pos; dead chunk blocks
        ckg = jnp.zeros((b, kvh, c, d), q.dtype)
        cvg = ckg
        cpos = jnp.full((b, 1, c), -1.0, jnp.float32)
        chunk_start = (jnp.max(jnp.where(valid, positions, -1), axis=1)
                       + 1).astype(jnp.int32)
    lo = jnp.where(win_arr[0] > 0,
                   jnp.maximum(minpos - win_arr[0] + 1, 0),
                   0).astype(jnp.int32)

    use_alibi = alibi_slopes is not None
    if use_alibi:
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(kvh, group)
        slopes = jnp.tile(sl, (1, c)).reshape(kvh, 1, rows)
    else:
        slopes = jnp.zeros((kvh, 1, rows), jnp.float32)

    if pages_per_step is None:
        pages_per_step = int(os.environ.get("DS_TPU_PAGES_PER_STEP", "8"))
    K = max(1, min(int(pages_per_step), mb))
    grid_steps = -(-mb // K)
    grid = (b, kvh, grid_steps)

    def q_map(bi, hi, ji, lyr_, bt, lens, lo_, w_):
        return (bi, hi, 0, 0)

    def kv_map_t(t):
        # t-th page of this grid step's K-page group. The page lookup is
        # clamped into the sequence's LIVE range [lo/bs, ceil(cs/bs)-1]:
        # steps outside it all map to the same page, and Pallas elides the
        # DMA when consecutive grid steps index an identical block — dead
        # pages (beyond the sequence, or below the sliding window) cost no
        # HBM traffic. Correctness is unaffected: the kernel masks by the
        # LOGICAL slot (ji*K+t), not the fetched page.
        def kv_map(bi, hi, ji, lyr_, bt, cs, lo_, w_):
            last = jnp.maximum((cs[bi] + page_size - 1) // page_size - 1, 0)
            jt = jnp.clip(ji * K + t, lo_[bi] // page_size, last)
            return (lyr_[0], hi, bt[bi, jt], 0, 0)
        return kv_map

    def pos_map(bi, hi, ji, lyr_, bt, lens, lo_, w_):
        return (bi, 0, 0)

    def slope_map(bi, hi, ji, lyr_, bt, lens, lo_, w_):
        return (hi, 0, 0)

    def chunk_map(bi, hi, ji, lyr_, bt, lens, lo_, w_):
        return (bi, hi, 0, 0)

    page_spec = [pl.BlockSpec((1, 1, 1, page_size, d), kv_map_t(t))
                 for t in range(K)]
    out = pl.pallas_call(
        functools.partial(_paged_kernel, page_size=page_size,
                          grid_steps=grid_steps, pages_per_step=K,
                          scale=scale, softcap=softcap,
                          use_alibi=use_alibi),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rows, d), q_map),
                *page_spec,                                    # K k-pages
                *page_spec,                                    # K v-pages
                pl.BlockSpec((1, 1, rows), pos_map),
                pl.BlockSpec((1, 1, rows), slope_map),
                pl.BlockSpec((1, 1, c, d), chunk_map),
                pl.BlockSpec((1, 1, c, d), chunk_map),
                pl.BlockSpec((1, 1, c), pos_map),
            ],
            out_specs=pl.BlockSpec((1, 1, rows, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh, rows, d), q.dtype),
        interpret=jax.default_backend() != "tpu",
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
    )(lyr, block_tables, chunk_start, lo, win_arr, qg,
      *([kpool] * K), *([vpool] * K), pos_rep,
      slopes, ckg, cvg, cpos)
    # (B, KVH, C*G, D) → (B, C, H, D)
    return out.reshape(b, kvh, c, group, d).transpose(0, 2, 1, 3, 4).reshape(
        b, c, h, d)


def paged_decode_attention(q, kpool, vpool, block_tables, seq_lens, *,
                           scale=None, window=0, alibi_slopes=None,
                           softcap=0.0):
    """Single-token decode wrapper: q (B, H, D), seq_lens (B,) tokens in
    each sequence INCLUDING the one being decoded. Returns (B, H, D)."""
    positions = (seq_lens - 1).astype(jnp.int32)[:, None]      # (B, 1)
    out = paged_ragged_attention(q[:, None], kpool, vpool, block_tables,
                                 positions, scale=scale, window=window,
                                 alibi_slopes=alibi_slopes, softcap=softcap)
    return out[:, 0]
