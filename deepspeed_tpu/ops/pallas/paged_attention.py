"""Paged decode attention.

Analog of ``inference/v2/kernels/ragged_ops/blocked_flash`` (flash attention
over paged KV atoms). Current implementation is the XLA gather path used by
``inference/v2/model_runner.py`` (gather pages → masked attention); the
Pallas kernel slot exists so the op-builder table and future in-place page
reads share this import point.
"""

from ...inference.v2.model_runner import _paged_attention as paged_attention  # noqa: F401
