"""Evoformer (DS4Science) attention.

Analog of ``deepspeed/ops/deepspeed4science/evoformer_attn.py``
(``DS4Sci_EvoformerAttention:88``, CUTLASS kernels under
``csrc/deepspeed4science/evoformer_attn``): attention over AlphaFold-style
5-D activations (batch, rows, seq, heads, dim) with up to two additive
biases — a per-row mask bias (B, N, 1, 1, S) and a pairwise triangle bias
(B, 1, H, S, S).

TPU mapping: the reference needs a custom kernel because a materialized
(B, N, H, S, S) logits tensor blows past HBM at MSA scale; here the query
dimension is processed in ``lax.scan`` chunks so peak memory is
O(chunk · S) per (row, head) while XLA fuses the bias adds and softmax into
the chunk matmuls. Fully differentiable (scan autodiff); numerics are fp32
softmax like the reference kernel.
"""

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .attention import _use_pallas


def _bias_shapes(q):
    b, n, s = q.shape[0], q.shape[1], q.shape[2]
    h = q.shape[3]
    return (b, n, 1, 1, s), (b, 1, h, s, s)


def DS4Sci_EvoformerAttention(q, k, v, biases: Sequence = (), chunk: int = 256):
    """q/k/v: (B, N, S, H, D); biases: up to two of
    [(B, N, 1, 1, S) mask bias, (B, 1, H, S, S) pair bias].
    Returns (B, N, S, H, D) in q's dtype.

    Dispatch: MXU-friendly shapes run the fused Pallas bias-flash forward
    (``pallas/evoformer_flash.py`` — logits never hit HBM) with a
    query-chunked recompute backward; other shapes take the chunked XLA
    path end-to-end. The env kill switch is read at Python call time
    (OUTSIDE the jitted internals) so toggling it mid-process works, like
    every other Pallas dispatcher in this repo.
    """
    biases = [b for b in biases if b is not None]
    assert len(biases) <= 2, "at most two biases (mask, pair)"
    bias1 = bias2 = None
    s1, s2 = _bias_shapes(q)
    for b in biases:
        if b.shape == s1:
            bias1 = b
        elif b.shape == s2:
            bias2 = b
        else:
            raise ValueError(f"bias shape {b.shape} matches neither mask "
                             f"{s1} nor pair {s2}")
    from .pallas.evoformer_flash import evoformer_flash_supported
    fb_key = (q.shape, str(q.dtype))
    if (_use_pallas() and evoformer_flash_supported(q.shape[2], q.shape[4])
            and fb_key not in _EVO_FALLBACK_WARNED):
        try:
            return _evo_attn_jit(q, k, v, bias1, bias2, chunk)
        except Exception as e:
            # same contract as the flash-attention dispatcher: a kernel
            # failure downgrades to the XLA path LOUDLY, once per shape
            # (the shape also skips straight to the XLA path afterwards —
            # no per-step recompile attempts)
            _EVO_FALLBACK_WARNED.add(fb_key)
            import logging
            logging.getLogger("DeepSpeedTPU").warning(
                "Pallas evoformer attention FAILED for shape %s (%s: %s); "
                "falling back to the chunked XLA path. Set "
                "DS_TPU_DISABLE_PALLAS=1 to silence.",
                q.shape, type(e).__name__, e)
    return _chunked_jit(q, k, v, bias1, bias2, chunk)


_EVO_FALLBACK_WARNED = set()


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _evo_attn(q, k, v, bias1, bias2, chunk):
    from .pallas.evoformer_flash import evoformer_flash_fwd
    d = q.shape[-1]
    out = evoformer_flash_fwd(
        jnp.moveaxis(q, 3, 2), jnp.moveaxis(k, 3, 2), jnp.moveaxis(v, 3, 2),
        bias1, bias2, scale=d ** -0.5)
    return jnp.moveaxis(out, 2, 3)


def _evo_attn_fwd_rule(q, k, v, bias1, bias2, chunk):
    return _evo_attn(q, k, v, bias1, bias2, chunk), (q, k, v, bias1, bias2)


def _evo_attn_bwd_rule(chunk, residuals, g):
    # recompute through the chunked XLA formulation: identical math, peak
    # memory O(chunk * S) per (row, head); dBias1/dBias2 fall out of
    # autodiff (the reference kernel's dB outputs)
    q, k, v, bias1, bias2 = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_, b1, b2: _chunked(q_, k_, v_, b1, b2, chunk),
        q, k, v, bias1, bias2)
    return vjp(g)


_evo_attn.defvjp(_evo_attn_fwd_rule, _evo_attn_bwd_rule)

_evo_attn_jit = jax.jit(_evo_attn, static_argnums=(5,))


@functools.partial(jax.jit, static_argnames=("chunk",))
def _chunked_jit(q, k, v, bias1, bias2, chunk):
    return _chunked(q, k, v, bias1, bias2, chunk)


def _chunked(q, k, v, bias1, bias2, chunk: int = 256):
    bdim, n, s, h, d = q.shape
    scale = d ** -0.5
    # (B, N, S, H, D) → (B, N, H, S, D)
    qt = jnp.moveaxis(q, 3, 2) * scale
    kt = jnp.moveaxis(k, 3, 2)
    vt = jnp.moveaxis(v, 3, 2)

    chunk = min(chunk, s)
    pad = (-s) % chunk
    if pad:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0)))
    n_chunks = qt.shape[3] // chunk
    q_chunks = qt.reshape(bdim, n, h, n_chunks, chunk, d)
    q_chunks = jnp.moveaxis(q_chunks, 3, 0)          # (C, B, N, H, chunk, D)
    b2_chunks = None
    if bias2 is not None:
        b2 = bias2
        if pad:
            b2 = jnp.pad(b2, ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0)))
        b2_chunks = jnp.moveaxis(
            b2.reshape(bdim, 1, h, n_chunks, chunk, s), 3, 0)

    def one_chunk(qc, b2c):
        logits = jnp.einsum("bnhqd,bnhkd->bnhqk", qc.astype(jnp.float32),
                            kt.astype(jnp.float32))
        if bias1 is not None:
            logits = logits + bias1.astype(jnp.float32)   # (B,N,1,1,S) broadcast
        if b2c is not None:
            logits = logits + b2c.astype(jnp.float32)     # (B,1,H,chunk,S)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bnhqk,bnhkd->bnhqd", probs.astype(vt.dtype), vt)

    if n_chunks == 1:
        out = one_chunk(q_chunks[0], None if b2_chunks is None else b2_chunks[0])
    else:
        def body(_, xs):
            if b2_chunks is None:
                qc = xs
                return None, one_chunk(qc, None)
            qc, b2c = xs
            return None, one_chunk(qc, b2c)

        xs = q_chunks if b2_chunks is None else (q_chunks, b2_chunks)
        _, outs = jax.lax.scan(body, None, xs)   # (C, B, N, H, chunk, D)
        out = jnp.moveaxis(outs, 0, 3).reshape(bdim, n, h, n_chunks * chunk, d)
    if pad:
        out = out[:, :, :, :s]
    return jnp.moveaxis(out, 2, 3).astype(q.dtype)     # back to (B, N, S, H, D)
