"""ctypes surface of the native host Adam/Adagrad/Lion kernels.

Analog of the reference's DeepSpeedCPUAdam binding (``csrc/adam/cpu_adam.cpp``
→ ``deepspeed.ops.adam.DeepSpeedCPUAdam``): flat fp32 buffers updated in
place on the host while the accelerator runs ahead.
"""

import ctypes

import numpy as np

from .op_builder import CPUAdamBuilder

_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        _lib = CPUAdamBuilder().load(verbose=False)
        f = ctypes.POINTER(ctypes.c_float)
        _lib.ds_cpu_adam_step.argtypes = [f, f, f, f, ctypes.c_int64, ctypes.c_int64,
                                          ctypes.c_float, ctypes.c_float, ctypes.c_float,
                                          ctypes.c_float, ctypes.c_float,
                                          ctypes.c_int, ctypes.c_int]
        _lib.ds_cpu_adagrad_step.argtypes = [f, f, f, ctypes.c_int64, ctypes.c_float,
                                             ctypes.c_float, ctypes.c_float]
        _lib.ds_cpu_lion_step.argtypes = [f, f, f, ctypes.c_int64, ctypes.c_float,
                                          ctypes.c_float, ctypes.c_float, ctypes.c_float]
    return _lib


def _fp(a: np.ndarray):
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def cpu_adam_step(params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
                  exp_avg_sq: np.ndarray, step: int, lr: float,
                  betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 0.0,
                  adamw_mode: bool = True, bias_correction: bool = True):
    """In-place AdamW update on host fp32 buffers."""
    lib = _get_lib()
    lib.ds_cpu_adam_step(_fp(params), _fp(grads), _fp(exp_avg), _fp(exp_avg_sq),
                         params.size, step, lr, betas[0], betas[1], eps, weight_decay,
                         int(adamw_mode), int(bias_correction))


def cpu_adagrad_step(params, grads, exp_avg_sq, lr, eps=1e-10, weight_decay=0.0):
    _get_lib().ds_cpu_adagrad_step(_fp(params), _fp(grads), _fp(exp_avg_sq),
                                   params.size, lr, eps, weight_decay)


def cpu_lion_step(params, grads, exp_avg, lr, betas=(0.9, 0.99), weight_decay=0.0):
    _get_lib().ds_cpu_lion_step(_fp(params), _fp(grads), _fp(exp_avg),
                                params.size, lr, betas[0], betas[1], weight_decay)
