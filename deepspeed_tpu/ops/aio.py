"""Python surface of the async I/O engine.

Analog of the reference's ``deepspeed.ops.op_builder.AsyncIOBuilder`` module
(``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` handle API): submit async
reads/writes of numpy buffers against files, wait for completion.
"""

import ctypes
import os
from typing import Optional

import numpy as np

from .op_builder import AsyncIOBuilder

_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        _lib = AsyncIOBuilder().load(verbose=False)
        _lib.ds_aio_handle_new.restype = ctypes.c_void_p
        _lib.ds_aio_handle_new.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
        _lib.ds_aio_handle_free.argtypes = [ctypes.c_void_p]
        for fn in ("ds_aio_pread", "ds_aio_pwrite"):
            getattr(_lib, fn).restype = ctypes.c_int64
            getattr(_lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        _lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        _lib.ds_aio_error_count.restype = ctypes.c_int64
        _lib.ds_aio_error_count.argtypes = [ctypes.c_void_p]
        _lib.ds_aio_inflight.restype = ctypes.c_int64
        _lib.ds_aio_inflight.argtypes = [ctypes.c_void_p]
    return _lib


class AsyncIOHandle:
    """Thread-pooled positional I/O handle (reference aio_handle)."""

    def __init__(self, queue_depth: int = 8, block_size: int = 1 << 20,
                 use_direct: bool = False):
        self._lib = _get_lib()
        self._h = self._lib.ds_aio_handle_new(queue_depth, block_size, int(use_direct))
        self._pinned = []  # keep buffers alive while requests are in flight

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ds_aio_wait(self._h)
                self._lib.ds_aio_handle_free(self._h)
                self._h = None
        except Exception:
            pass

    def _buf_ptr(self, arr: np.ndarray):
        assert arr.flags["C_CONTIGUOUS"], "aio buffers must be C-contiguous"
        self._pinned.append(arr)
        return arr.ctypes.data_as(ctypes.c_void_p)

    def async_pwrite(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        return self._lib.ds_aio_pwrite(self._h, path.encode(), self._buf_ptr(arr),
                                       arr.nbytes, offset)

    def async_pread(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        return self._lib.ds_aio_pread(self._h, path.encode(), self._buf_ptr(arr),
                                      arr.nbytes, offset)

    def wait(self) -> int:
        self._lib.ds_aio_wait(self._h)
        errs = int(self._lib.ds_aio_error_count(self._h))
        self._pinned.clear()
        return errs

    def sync_pwrite(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pwrite(arr, path, offset)
        return self.wait()

    def sync_pread(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pread(arr, path, offset)
        return self.wait()

    @property
    def inflight(self) -> int:
        return int(self._lib.ds_aio_inflight(self._h))
