"""Op builder registry. Analog of ``op_builder/__init__.py`` ALL_OPS table."""

from .builder import NativeOpBuilder, OpBuilder, PallasOpBuilder


class FusedAdamBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("fused_adam", "deepspeed_tpu.ops.pallas.fused_adam")


class FlashAttnBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("flash_attn", "deepspeed_tpu.ops.pallas.flash_attention")


class PagedAttnBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("paged_attn", "deepspeed_tpu.ops.pallas.paged_attention")


class QuantizerBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("quantizer", "deepspeed_tpu.ops.pallas.quantizer")


class FPQuantizerBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("fp_quantizer", "deepspeed_tpu.ops.pallas.fp_quantizer")


class GroupedGemmBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("grouped_gemm", "deepspeed_tpu.ops.pallas.grouped_gemm")


class RingAttnBuilder(PallasOpBuilder):
    def __init__(self):
        super().__init__("ring_attn", "deepspeed_tpu.ops.pallas.ring_attention")


class CPUAdamBuilder(NativeOpBuilder):
    """AVX-vectorized host Adam for ZeRO-Offload (reference csrc/adam/cpu_adam.cpp)."""

    def __init__(self):
        super().__init__("cpu_adam")

    def sources(self):
        return ["deepspeed_tpu/ops/csrc/adam/cpu_adam.cpp"]

    def include_paths(self):
        return ["deepspeed_tpu/ops/csrc"]

    def cxx_args(self):
        import platform
        args = ["-O3", "-std=c++17", "-fPIC", "-fopenmp", "-g"]
        if platform.machine() == "x86_64":
            args += ["-march=native"]
        return args


class AsyncIOBuilder(NativeOpBuilder):
    """Async NVMe/file IO engine (reference csrc/aio)."""

    def __init__(self):
        super().__init__("async_io")

    def sources(self):
        return ["deepspeed_tpu/ops/csrc/aio/deepspeed_aio.cpp"]

    def include_paths(self):
        return ["deepspeed_tpu/ops/csrc"]

    def extra_ldflags(self):
        return ["-lpthread"]


ALL_OPS = {
    cls.__name__: cls
    for cls in [
        FusedAdamBuilder, FlashAttnBuilder, PagedAttnBuilder, QuantizerBuilder, FPQuantizerBuilder,
        GroupedGemmBuilder, RingAttnBuilder, CPUAdamBuilder, AsyncIOBuilder
    ]
}

__all__ = ["OpBuilder", "PallasOpBuilder", "NativeOpBuilder", "ALL_OPS"] + list(ALL_OPS.keys())


def build_all(verbose: bool = True, ops=None):
    """Ahead-of-time build of every (compatible) op — the analog of the
    reference's prebuild path (``DS_BUILD_OPS=1`` install, builder.py:513):
    native extensions are compiled into the build cache NOW instead of at
    first use, so multi-process launches don't race the JIT compile and
    air-gapped deploys ship warm caches. Returns {name: "ok" | "skipped:
    <why>" | "failed: <err>"}."""
    results = {}
    for cls_name, cls in ALL_OPS.items():
        if ops and cls_name not in ops:
            continue
        b = cls()
        if not b.is_compatible(verbose=verbose):
            results[b.name] = f"skipped: {b.error_log or 'incompatible'}"
            continue
        try:
            b.load(verbose=verbose)
            results[b.name] = "ok"
        except Exception as e:
            results[b.name] = f"failed: {str(e)[:200]}"
    return results
