"""Op builder framework.

Analog of ``op_builder/builder.py:109`` (OpBuilder: sources/load/jit_load/
is_compatible). Two TPU-native builder families:

- :class:`PallasOpBuilder` — "building" is importing a Python module of Pallas
  kernels (compiled lazily by XLA at first trace); ``is_compatible`` probes the
  backend (TPU vs CPU-interpret mode).
- :class:`NativeOpBuilder` — compiles C++ host code (CPU Adam, async IO) with
  g++ into a shared library loaded via ctypes; this is the analog of the
  reference's torch cpp_extension JIT path (``builder.py:532 jit_load``).
"""

import importlib
import os
import shutil
import subprocess
import sys
import time
from abc import ABC, abstractmethod

from ...utils.logging import logger


class OpBuilder(ABC):

    def __init__(self, name):
        self.name = name
        self.jit_mode = False
        self.error_log = None

    @abstractmethod
    def absolute_name(self):
        """Importable module name of the built op, e.g. deepspeed_tpu.ops.pallas.fused_adam"""
        ...

    def sources(self):
        return []

    def include_paths(self):
        return []

    def is_compatible(self, verbose=False):
        return True

    def extra_ldflags(self):
        return []

    def cxx_args(self):
        return ["-O3", "-std=c++17", "-fPIC", "-fopenmp"]

    def load(self, verbose=True):
        return self.jit_load(verbose=verbose)

    @abstractmethod
    def jit_load(self, verbose=True):
        ...

    def command_exists(self, cmd):
        return shutil.which(cmd) is not None


class PallasOpBuilder(OpBuilder):
    """Builder whose artifact is a Python module of Pallas/XLA kernels."""

    def __init__(self, name, module):
        super().__init__(name)
        self.module = module

    def absolute_name(self):
        return self.module

    def is_compatible(self, verbose=False):
        try:
            importlib.import_module(self.module)
            return True
        except Exception as e:
            if verbose:
                logger.warning(f"op {self.name} incompatible: {e}")
            self.error_log = str(e)
            return False

    def jit_load(self, verbose=True):
        start = time.time()
        mod = importlib.import_module(self.module)
        if verbose:
            logger.info(f"Loading op {self.name} took {time.time() - start:.3f} seconds")
        return mod


def _repo_root():
    return os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))


class NativeOpBuilder(OpBuilder):
    """g++-compiled host extension, loaded via ctypes.

    Build artifacts land in ``~/.cache/deepspeed_tpu/<name>/`` (analog of
    TORCH_EXTENSIONS_DIR).
    """

    BUILD_ROOT = os.environ.get("DS_TPU_BUILD_DIR", os.path.expanduser("~/.cache/deepspeed_tpu"))

    def __init__(self, name):
        super().__init__(name)

    def absolute_name(self):
        return f"deepspeed_tpu.ops.native.{self.name}"

    def lib_path(self):
        return os.path.join(self.BUILD_ROOT, self.name, f"lib{self.name}.so")

    def is_compatible(self, verbose=False):
        if not self.command_exists("g++"):
            self.error_log = "g++ not found"
            return False
        return True

    def _resolved_sources(self):
        # sources() are repo-relative: resolve against the package root, not
        # the process CWD (engines are routinely built from other dirs)
        return [s if os.path.isabs(s) else os.path.join(_repo_root(), s)
                for s in self.sources()]

    def _needs_rebuild(self):
        lib = self.lib_path()
        if not os.path.exists(lib):
            return True
        lib_mtime = os.path.getmtime(lib)
        missing = [s for s in self._resolved_sources() if not os.path.exists(s)]
        if missing:
            raise FileNotFoundError(
                f"op '{self.name}': source file(s) {missing} not found — "
                "refusing to load a stale library built from removed sources")
        return any(os.path.getmtime(src) > lib_mtime
                   for src in self._resolved_sources())

    def jit_load(self, verbose=True):
        import ctypes
        if self._needs_rebuild():
            start = time.time()
            os.makedirs(os.path.dirname(self.lib_path()), exist_ok=True)
            srcs = self._resolved_sources()
            incs = [f"-I{os.path.join(_repo_root(), i) if not os.path.isabs(i) else i}" for i in self.include_paths()]
            cmd = ["g++", "-shared", *self.cxx_args(), *incs, *srcs, "-o", self.lib_path(), *self.extra_ldflags()]
            if verbose:
                logger.info(f"Building op {self.name}: {' '.join(cmd)}")
            result = subprocess.run(cmd, capture_output=True, text=True)
            if result.returncode != 0:
                self.error_log = result.stderr
                raise RuntimeError(f"Failed to build {self.name}:\n{result.stderr}")
            if verbose:
                logger.info(f"Time to build op {self.name}: {time.time() - start:.3f} seconds")
        return ctypes.CDLL(self.lib_path())

    def sources(self):
        return []
