"""``python -m deepspeed_tpu.ops.op_builder`` — prebuild + report.

Analog of the reference's ``ds_report`` op table + ``DS_BUILD_OPS``
prebuild: probes every registered builder, compiles the native ones
ahead of time, and prints one status line per op. Exits nonzero if an op
named via ``--op`` fails to build.
"""

import argparse
import sys

from . import ALL_OPS, build_all


def main(argv=None):
    ap = argparse.ArgumentParser(description="Prebuild deepspeed_tpu ops")
    ap.add_argument("--op", action="append", default=None,
                    help="builder class name (repeatable); default: all")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    results = build_all(verbose=not args.quiet, ops=args.op)
    width = max(len(n) for n in results)
    rc = 0
    for name, status in results.items():
        print(f"{name:<{width}}  {status}")
        if args.op and not status.startswith(("ok", "skipped")):
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
