"""Autotuning search strategies.

Analog of ``deepspeed/autotuning/tuner/`` (GridSearchTuner, RandomTuner,
ModelBasedTuner over experiment lists): a tuner proposes which experiment
(config candidate) to run next and records measured metrics; the Autotuner
drives trials through it. The model-based strategy fits a saturating
throughput curve t(mb) = mb / (a + b*mb) per discrete setting group and
explores the candidate with the highest predicted metric — the same
explore/exploit shape as the reference's cost-model tuner without the
XGBoost dependency.
"""

import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

Experiment = Dict[str, Any]


class BaseTuner:
    """Propose-next / record-result protocol."""

    def __init__(self, experiments: Sequence[Experiment], seed: int = 0):
        self.experiments = list(experiments)
        self.results: List[Tuple[Experiment, Optional[float]]] = []
        self._tried = set()
        self._rng = random.Random(seed)

    def _key(self, exp: Experiment):
        return tuple(sorted(exp.items()))

    def has_next(self) -> bool:
        return len(self._tried) < len(self.experiments)

    def next_trial(self) -> Experiment:
        raise NotImplementedError

    def update(self, exp: Experiment, metric: Optional[float]):
        self._tried.add(self._key(exp))
        self.results.append((exp, metric))

    def best(self) -> Optional[Tuple[Experiment, float]]:
        done = [(e, m) for e, m in self.results if m is not None]
        return max(done, key=lambda em: em[1]) if done else None


class GridSearchTuner(BaseTuner):
    """Exhaustive, in declaration order (reference GridSearchTuner)."""

    def next_trial(self) -> Experiment:
        for e in self.experiments:
            if self._key(e) not in self._tried:
                return e
        raise StopIteration


class RandomTuner(BaseTuner):
    """Uniform random without replacement (reference RandomTuner)."""

    def next_trial(self) -> Experiment:
        remaining = [e for e in self.experiments if self._key(e) not in self._tried]
        if not remaining:
            raise StopIteration
        return self._rng.choice(remaining)


class ModelBasedTuner(BaseTuner):
    """Cost-model guided (reference ModelBasedTuner).

    Groups experiments by their non-numeric settings (e.g. zero stage);
    within a group, fits t(mb) = mb / (a + b*mb) to the measured points
    (linear least squares on mb/t = a + b*mb) and predicts the metric for
    untried micro-batches. Proposes the untried experiment with the highest
    predicted metric; unseen groups get one exploratory probe first.
    """

    def __init__(self, experiments, numeric_key: str = "micro_batch", seed: int = 0):
        super().__init__(experiments, seed)
        self.numeric_key = numeric_key

    def _group(self, exp: Experiment):
        return tuple(sorted((k, v) for k, v in exp.items() if k != self.numeric_key))

    def _fit(self, pts: List[Tuple[float, float]]):
        # least squares for mb/t = a + b*mb
        if len(pts) == 1:
            mb, t = pts[0]
            return mb / t, 0.0
        xs = [mb for mb, _ in pts]
        ys = [mb / t for mb, t in pts]
        n = len(xs)
        sx, sy = sum(xs), sum(ys)
        sxx = sum(x * x for x in xs)
        sxy = sum(x * y for x, y in zip(xs, ys))
        denom = n * sxx - sx * sx
        if abs(denom) < 1e-12:
            return sy / n, 0.0
        b = (n * sxy - sx * sy) / denom
        a = (sy - b * sx) / n
        return a, b

    def _predict(self, exp: Experiment) -> Optional[float]:
        pts = [(e[self.numeric_key], m) for e, m in self.results
               if m is not None and self._group(e) == self._group(exp)]
        if not pts:
            return None
        a, b = self._fit(pts)
        mb = exp[self.numeric_key]
        denom = a + b * mb
        if denom <= 0:
            return 0.0
        return mb / denom

    def next_trial(self) -> Experiment:
        remaining = [e for e in self.experiments if self._key(e) not in self._tried]
        if not remaining:
            raise StopIteration
        # one exploratory probe (smallest numeric) for any unseen group
        for e in sorted(remaining, key=lambda x: x[self.numeric_key]):
            if self._predict(e) is None:
                return e
        return max(remaining, key=lambda e: self._predict(e))


TUNERS: Dict[str, type] = {
    "gridsearch": GridSearchTuner,
    "random": RandomTuner,
    "model_based": ModelBasedTuner,
}


def build_tuner(name: str, experiments, **kw) -> BaseTuner:
    if name not in TUNERS:
        raise ValueError(f"unknown tuner strategy {name!r}; known: {sorted(TUNERS)}")
    return TUNERS[name](experiments, **kw)
