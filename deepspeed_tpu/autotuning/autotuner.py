"""Autotuner: micro-batch / ZeRO-stage search.

Analog of ``deepspeed/autotuning/autotuner.py:42`` (``tune:404``, model-info
profiling ``:663``, micro-batch search ``:741``). The reference launches
separate experiment jobs; here trials run in-process (one compiled step per
candidate, timed on the live mesh) which is cheap under XLA's compile cache.
Search strategy: profile model memory → enumerate feasible (zero_stage,
micro_batch) pairs → measure tokens/sec → pick the fastest.
"""

import itertools
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import logger

DEFAULT_MICRO_BATCHES = (1, 2, 4, 8, 16, 32)


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any], seq_len: int = 512,
                 micro_batch_candidates=DEFAULT_MICRO_BATCHES,
                 zero_stage_candidates=(0, 1, 2, 3), steps_per_trial: int = 3,
                 strategy: str = "heuristic", max_trials: Optional[int] = None,
                 remat_candidates=("none", "dots")):
        self.model = model
        self.base_config = dict(base_config)
        self.seq_len = seq_len
        self.mb_candidates = list(micro_batch_candidates)
        self.stage_candidates = list(zero_stage_candidates)
        self.steps_per_trial = steps_per_trial
        self.strategy = strategy          # "heuristic" | tuner.TUNERS names
        self.max_trials = max_trials
        # remat joins the search space: on HBM-bound parts saving only
        # matmul outputs ("dots") BEATS saving everything (round-5 measured
        # +7% on v5e — saved-activation traffic, not recompute FLOPs, was
        # the binding constraint), so it is a throughput knob, not only a
        # memory knob
        self.remat_candidates = list(remat_candidates)
        self.results: List[Dict[str, Any]] = []

    def model_info(self) -> Dict[str, Any]:
        """Analog of the model-info profile run (:663)."""
        n = self.model.param_count()
        return {"num_params": n,
                "fp32_mem_gb": 4 * n / 2 ** 30,
                "adam_state_gb": 8 * n / 2 ** 30}

    def _trial(self, zero_stage: int, micro_batch: int,
               remat: str = "none") -> Optional[float]:
        import jax
        import deepspeed_tpu as ds
        from ..utils import groups
        import deepspeed_tpu.comm.comm as dc
        groups.reset_mesh()
        dc.cdb = None
        dp = max(1, len(jax.devices()))
        cfg = dict(self.base_config)
        cfg.update({
            "train_micro_batch_size_per_gpu": micro_batch,
            "gradient_accumulation_steps": 1,
            "train_batch_size": micro_batch * dp,
            "zero_optimization": {"stage": zero_stage},
            "activation_checkpointing": {"policy": remat},
            "steps_per_print": 10 ** 9,
        })
        cfg_owner = self.model
        try:
            from ..models.transformer import CausalLM
            if not isinstance(cfg_owner, CausalLM) and isinstance(
                    getattr(cfg_owner, "student", None), CausalLM):
                cfg_owner = cfg_owner.student   # the object the engine mutates
        except Exception:
            pass
        prev_remat = getattr(getattr(cfg_owner, "cfg", None), "remat", None)
        try:
            engine, _, _, _ = ds.initialize(model=self.model, config=cfg)
            rng = np.random.default_rng(0)
            vocab = self.model.cfg.vocab_size

            def batch():
                ids = rng.integers(0, vocab, (cfg["train_batch_size"], self.seq_len))
                return {"input_ids": ids, "labels": ids}

            loss = engine.train_batch(batch())   # compile
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                loss = engine.train_batch(batch())
            jax.block_until_ready(loss)
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return cfg["train_batch_size"] * self.seq_len / dt
        except Exception as e:
            logger.warning(f"trial zero={zero_stage} mb={micro_batch} "
                           f"remat={remat} failed: {str(e)[:120]}")
            return None
        finally:
            # the engine writes the policy into the model cfg; restore ON THE
            # SAME OBJECT it mutates (setattr on a delegating wrapper would
            # create a shadow attribute and leak the policy)
            if prev_remat is not None and hasattr(cfg_owner, "cfg"):
                cfg_owner.cfg = cfg_owner.cfg.replace(remat=prev_remat)

    def tune(self, fast: bool = True) -> Dict[str, Any]:
        """Run the search; returns the best config patch (reference tune:404).

        ``strategy="heuristic"`` keeps the monotone micro-batch climb with
        early stops; "gridsearch"/"random"/"model_based" route trial order
        through ``autotuning/tuner.py`` (reference tuner strategies), with
        ``max_trials`` as the experiment budget."""
        info = self.model_info()
        logger.info(f"autotuning: model={info['num_params'] / 1e6:.1f}M params")
        if self.strategy != "heuristic":
            return self._tune_with_strategy()
        stages = [self.stage_candidates[0]] if fast and len(self.stage_candidates) > 1 \
            else self.stage_candidates
        best = None
        base_remat = self.remat_candidates[0] if self.remat_candidates else "none"
        for stage in stages:
            prev = 0.0
            for mb in self.mb_candidates:
                tput = self._trial(stage, mb, base_remat)
                self.results.append({"zero_stage": stage, "micro_batch": mb,
                                     "remat": base_remat,
                                     "tokens_per_sec": tput})
                if tput is None:
                    break            # OOM / failure: larger batches won't fit
                if best is None or tput > best["tokens_per_sec"]:
                    best = {"zero_stage": stage, "micro_batch": mb,
                            "tokens_per_sec": tput}
                if tput < prev * 1.05:
                    break            # diminishing returns: stop scaling mb
                prev = tput
        if best is None:
            raise RuntimeError("autotuning: no trial succeeded")
        # remat post-pass at the winning (stage, mb): one extra trial per
        # alternative policy — the cheap form of the full product search
        best["remat"] = base_remat
        for remat in self.remat_candidates[1:]:
            tput = self._trial(best["zero_stage"], best["micro_batch"], remat)
            self.results.append({"zero_stage": best["zero_stage"],
                                 "micro_batch": best["micro_batch"],
                                 "remat": remat, "tokens_per_sec": tput})
            if tput is not None and tput > best["tokens_per_sec"]:
                best.update(tokens_per_sec=tput, remat=remat)
        logger.info(f"autotuning best: {best}")
        return {
            "train_micro_batch_size_per_gpu": best["micro_batch"],
            "zero_optimization": {"stage": best["zero_stage"]},
            "activation_checkpointing": {"policy": best["remat"]},
            "autotuning_results": self.results,
        }

    def _tune_with_strategy(self) -> Dict[str, Any]:
        from .tuner import build_tuner
        remats = self.remat_candidates or ["none"]
        experiments = [{"zero_stage": s, "micro_batch": mb, "remat": r}
                       for s in self.stage_candidates
                       for mb in self.mb_candidates
                       for r in remats]
        tuner = build_tuner(self.strategy, experiments)
        budget = self.max_trials or len(experiments)
        for _ in range(budget):
            if not tuner.has_next():
                break
            exp = tuner.next_trial()
            tput = self._trial(exp["zero_stage"], exp["micro_batch"],
                               exp.get("remat", "none"))
            tuner.update(exp, tput)
            self.results.append({**exp, "tokens_per_sec": tput})
        top = tuner.best()
        if top is None:
            raise RuntimeError("autotuning: no trial succeeded")
        best_exp, best_tput = top
        logger.info(f"autotuning[{self.strategy}] best: {best_exp} "
                    f"({best_tput:,.0f} tok/s)")
        return {
            "train_micro_batch_size_per_gpu": best_exp["micro_batch"],
            "zero_optimization": {"stage": best_exp["zero_stage"]},
            "activation_checkpointing": {"policy": best_exp.get("remat", "none")},
            "autotuning_results": self.results,
        }
