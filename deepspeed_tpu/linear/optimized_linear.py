"""Optimized linear / DS-LoRA.

Analog of ``deepspeed/linear/optimized_linear.py:18`` (OptimizedLinear) and
``:76`` (LoRAOptimizedLinear): base weight frozen (optionally quantized and
ZeRO-sharded over the data axis), trainable low-rank adapters on top.
Functional: ``init`` → params, ``apply`` → y = x W + (x A) B · (α/r).
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class LoRAConfig:
    """Reference ``linear/config.py`` LoRAConfig."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1


@dataclasses.dataclass
class QuantizationConfig:
    """Reference ``linear/config.py`` QuantizationConfig."""
    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512


class OptimizedLinear:
    """Factory matching the reference surface: returns a plain or LoRA
    linear depending on lora_config."""

    def __new__(cls, input_dim: int, output_dim: int, lora_config: Optional[LoRAConfig] = None,
                quantization_config: Optional[QuantizationConfig] = None, bias: bool = False,
                dtype=jnp.bfloat16):
        if lora_config is not None:
            return LoRAOptimizedLinear(input_dim, output_dim, lora_config,
                                       quantization_config, bias, dtype)
        return DenseLinear(input_dim, output_dim, bias, dtype)


class DenseLinear:
    def __init__(self, input_dim, output_dim, bias=False, dtype=jnp.bfloat16):
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.use_bias = bias
        self.dtype = dtype

    def init(self, rng):
        w = jax.random.normal(rng, (self.input_dim, self.output_dim),
                              jnp.float32) * (self.input_dim ** -0.5)
        p = {"weight": w}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return p

    def apply(self, params, x):
        y = x @ params["weight"].astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y


class LoRAOptimizedLinear:
    def __init__(self, input_dim, output_dim, lora_config: LoRAConfig,
                 quantization_config: Optional[QuantizationConfig] = None,
                 bias: bool = False, dtype=jnp.bfloat16):
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.cfg = lora_config
        self.qcfg = quantization_config
        self.use_bias = bias
        self.dtype = dtype
        self.scaling = lora_config.lora_alpha / lora_config.lora_r

    def init(self, rng, base_weight=None):
        r1, r2, r3 = jax.random.split(rng, 3)
        if base_weight is None:
            base_weight = jax.random.normal(
                r1, (self.input_dim, self.output_dim), jnp.float32) * (self.input_dim ** -0.5)
        if self.qcfg is not None:
            from ..inference.quantization.layers import QuantizedParameter
            base_weight = QuantizedParameter.quantize(
                base_weight, self.qcfg.q_bits, self.qcfg.group_size)
        params = {
            "base": base_weight,   # frozen
            "lora_a": jax.random.normal(r2, (self.input_dim, self.cfg.lora_r),
                                        jnp.float32) * (1.0 / math.sqrt(self.input_dim)),
            "lora_b": jnp.zeros((self.cfg.lora_r, self.output_dim), jnp.float32),
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def apply(self, params, x):
        from ..inference.quantization.layers import QuantizedParameter
        base = params["base"]
        if isinstance(base, QuantizedParameter):
            base = base.dequantized()
        y = x @ jax.lax.stop_gradient(base).astype(x.dtype)
        lora = (x @ params["lora_a"].astype(x.dtype)) @ params["lora_b"].astype(x.dtype)
        y = y + self.scaling * lora
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y

    def trainable_filter(self, path: str) -> bool:
        """Only adapters (and bias) train — base stays frozen."""
        return "lora_" in path or path.endswith("bias")
