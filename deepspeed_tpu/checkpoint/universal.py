"""Universal checkpoint: reshard-on-resume.

Analog of ``deepspeed/checkpoint/ds_to_universal.py`` (``main:469``, shard
extraction/merge) + ``universal_checkpoint.py:22`` (load_hp_checkpoint_state).
The reference converts (tp, pp, dp)-sharded torch checkpoints into an atomic
per-parameter format so training can resume on a different topology. In this
framework orbax already stores *logical* (unsharded) arrays — every
checkpoint is topology-free by construction — so "universal" conversion is
a layout flatten: one file per parameter/optimizer tensor plus an index.
Loading places each tensor with the CURRENT mesh's shardings, whatever the
dp/tp/pp/sp/ep sizes now are.
"""

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.logging import logger

INDEX_FILE = "universal_index.json"


def _flatten_with_paths(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}." if prefix or True else k))
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten_from_paths(flat: Dict[str, Any]):
    root: Dict[str, Any] = {}
    for path, value in flat.items():
        parts = path.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def jnp_asarray_like(ref, x):
    """Stage a host array with the dtype of an existing leaf, UNCOMMITTED
    (like the fresh tree it replaces) — a committed device_put would
    conflict with mesh-sharded co-arguments at the next jit call."""
    import jax.numpy as jnp
    return jnp.asarray(np.asarray(x, dtype=ref.dtype))


def _fetch_replicated(engine, tree):
    """Consolidate a (possibly ZeRO-sharded, possibly multi-process) state
    tree to host numpy, leaf by leaf: each leaf is replicated through a
    compiled identity before the fetch (device_get of a non-fully-addressable
    array is invalid in multi-process runs), and doing it per leaf bounds the
    transient device allocation to the largest single tensor instead of the
    whole fp32 optimizer state at once."""
    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            with engine.mesh:
                x = jax.jit(lambda t: t,
                            out_shardings=engine._replicated)(x)
        return np.asarray(jax.device_get(x))
    return jax.tree.map(fetch, tree)


def ds_to_universal(engine, output_dir: str):
    """Write the engine's full state as atomic per-parameter .npy files
    (reference ds_to_universal main:469). Multi-process: every rank joins
    the consolidation allgather; rank 0 writes the files."""
    os.makedirs(output_dir, exist_ok=True)
    if getattr(engine, "_infinity", None) is not None:
        # layer-streaming engines: per-parameter host trees straight from
        # the runner (group-layout-free — restorable under a different
        # stream_group_layers). No collectives involved, so non-writing
        # ranks skip the full host/NVMe state sweep entirely.
        if jax.process_index() != 0:
            return None
        state = engine._infinity.universal_state_dict()
    else:
        engine._swap_in_opt_state()
        opt_tree = (engine._host_optimizer.state_dict()
                    if getattr(engine, "_host_optimizer", None) is not None
                    else engine.opt_state)
        state = {
            "module": engine.module_state_dict(),
            "optimizer": _fetch_replicated(engine, opt_tree),
        }
    if getattr(engine, "_twinflow", None) is not None:
        # Twin-Flow keeps the device half of the optimizer state outside
        # _host_optimizer; without it a resume would run the device update
        # from freshly-initialized masters/moments.
        state["twinflow"] = _fetch_replicated(engine, engine._twinflow["dev_state"])
    if jax.process_index() != 0:
        return None
    index = {"params": [], "meta": {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "zero_stage": engine.zero_stage,
    }}
    for section in state:
        flat = _flatten_with_paths(state[section])
        for path, arr in flat.items():
            if arr is None:
                # masked leaves (Twin-Flow host/device split) — keep the
                # tree position in the index, no payload
                index["params"].append({"section": section, "path": path,
                                        "none": True})
                continue
            arr = np.asarray(arr)
            fname = f"{section}.{path}.npy".replace("/", "_")
            np.save(os.path.join(output_dir, fname), arr)
            index["params"].append({"section": section, "path": path, "file": fname,
                                    "shape": list(arr.shape), "dtype": str(arr.dtype)})
    with open(os.path.join(output_dir, INDEX_FILE), "w") as f:
        json.dump(index, f, indent=1)
    logger.info(f"universal checkpoint: {len(index['params'])} tensors → {output_dir}")
    return index


def load_universal_checkpoint(engine, load_dir: str, load_optimizer_states: bool = True):
    """Restore a universal checkpoint onto the engine's CURRENT topology
    (reference load_universal_checkpoint → universal_checkpoint.py:22)."""
    with open(os.path.join(load_dir, INDEX_FILE)) as f:
        index = json.load(f)
    sections: Dict[str, Dict[str, Optional[np.ndarray]]] = {
        "module": {}, "optimizer": {}}
    for entry in index["params"]:
        arr = (None if entry.get("none")
               else np.load(os.path.join(load_dir, entry["file"])))
        sections.setdefault(entry["section"], {})[entry["path"]] = arr
    module = _unflatten_from_paths(sections["module"])
    if getattr(engine, "_infinity", None) is not None:
        opt = (_unflatten_from_paths(sections["optimizer"])
               if load_optimizer_states and sections["optimizer"] else None)
        engine._infinity.load_universal_state_dict(module, opt)
        meta = index.get("meta", {})
        engine.global_steps = int(meta.get("global_steps", 0))
        engine.global_samples = int(meta.get("global_samples", 0))
        engine.micro_steps = int(meta.get("micro_steps", 0))
        return meta
    engine.module_params = jax.device_put(module, engine.param_shardings)
    if load_optimizer_states and sections["optimizer"]:
        opt = _unflatten_from_paths(sections["optimizer"])
        if getattr(engine, "_host_optimizer", None) is not None:
            # ZeRO-Offload(native): the saved tree IS the host optimizer's
            # state_dict ({"step", "slots"}). Route it into the host
            # masters/moments — assigning engine.opt_state (None and unused
            # in this mode) would leave the first train_batch to overwrite
            # the restored module params with stale init-time masters
            # (advisor r4, universal.py:114).
            dev = None
            if getattr(engine, "_twinflow", None) is not None:
                if "twinflow" not in sections:
                    # a silent skip would leave init-time device masters and
                    # revert the device-half weights on the next step (same
                    # bug class the host side now raises for)
                    raise ValueError(
                        "universal checkpoint has no 'twinflow' section but "
                        "this engine runs Twin-Flow (offload ratio < 1) — "
                        "the checkpoint was saved under a different "
                        "host/device split; resume with the saving config "
                        "or re-snapshot")
                dev = jax.tree.map(
                    jnp_asarray_like, engine._twinflow["dev_state"],
                    _unflatten_from_paths(sections["twinflow"]))
            engine._restore_host_optimizer_state(opt, dev)
        else:
            opt = jax.tree.map(lambda x, ref: np.asarray(x, dtype=ref.dtype),
                               opt, jax.tree.map(lambda s: s, jax.eval_shape(
                                   engine.optimizer.init, engine.model.abstract_params())))
            engine.opt_state = jax.device_put(opt, engine.opt_state_shardings)
    else:
        # optimizer state skipped (by flag, or absent from the checkpoint):
        # masters and device master-slots must track the freshly restored
        # weights, or the first update reverts them to init-time values
        engine._resync_masters_from_params()
    meta = index.get("meta", {})
    engine.global_steps = int(meta.get("global_steps", 0))
    engine.global_samples = int(meta.get("global_samples", 0))
    engine.micro_steps = int(meta.get("micro_steps", 0))
    return meta
