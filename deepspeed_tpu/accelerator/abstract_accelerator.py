"""Accelerator abstraction.

Analog of ``accelerator/abstract_accelerator.py:10`` (DeepSpeedAccelerator
ABC). The reference's ~70 abstract methods are torch-device-centric
(streams/events/caching allocator); on JAX the runtime owns those, so the
surface here keeps the portable subset: device identity/count, memory stats,
RNG, dtype support, communication backend name, and op-builder namespace
selection. Streams/events collapse to XLA's async dispatch: ``Stream`` is a
no-op context and ``Event`` records via ``block_until_ready`` fences.
"""

import abc
from contextlib import contextmanager


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ---- device APIs ----
    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    @abc.abstractmethod
    def device_name(self, device_index=None) -> str:
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    @abc.abstractmethod
    def devices(self):
        ...

    def current_device(self):
        return 0

    def current_device_name(self):
        return self.device_name(self.current_device())

    def set_device(self, device_index):
        ...

    def synchronize(self, device_index=None):
        import jax
        try:
            import jax.numpy as jnp
            jax.block_until_ready(jnp.zeros(()))
        except Exception:
            pass

    # ---- RNG ----
    def manual_seed(self, seed):
        import jax
        return jax.random.PRNGKey(seed)

    def initial_seed(self):
        return 0

    # ---- streams/events: XLA dispatch is already async ----
    @contextmanager
    def stream(self, stream=None):
        yield

    def Stream(self, *args, **kwargs):
        return None

    def Event(self, *args, **kwargs):
        return None

    def default_stream(self):
        return None

    def current_stream(self):
        return None

    # ---- memory ----
    @abc.abstractmethod
    def memory_stats(self, device_index=None) -> dict:
        ...

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def empty_cache(self):
        ...

    def reset_peak_memory_stats(self, device_index=None):
        ...

    # ---- dtype support ----
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    def is_fp8_supported(self) -> bool:
        return False

    def supported_dtypes(self):
        import jax.numpy as jnp
        dtypes = [jnp.float32]
        if self.is_fp16_supported():
            dtypes.append(jnp.float16)
        if self.is_bf16_supported():
            dtypes.append(jnp.bfloat16)
        if self.is_fp8_supported():
            dtypes.append(jnp.float8_e4m3fn)
        return dtypes

    # ---- misc ----
    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        ...

    def is_triton_supported(self) -> bool:
        return False

    def use_host_timers(self) -> bool:
        return True

    # ---- graph capture: jit IS the graph on XLA ----
    def create_graph(self):
        return None

    def capture_to_graph(self, graph, **kwargs):
        return _nullcontext()

    def replay_graph(self, graph):
        ...

    # ---- op builder namespace ----
    @abc.abstractmethod
    def op_builder_dir(self) -> str:
        ...

    @abc.abstractmethod
    def create_op_builder(self, class_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, class_name):
        ...


@contextmanager
def _nullcontext():
    yield
