"""TPU accelerator. Analog of ``accelerator/cuda_accelerator.py`` for TPU/XLA."""

import functools

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"  # ICI/DCN via XLA collectives

    def is_available(self):
        import jax
        try:
            return any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            return False

    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device_count(self):
        import jax
        return jax.device_count()

    def devices(self):
        import jax
        return jax.devices()

    def memory_stats(self, device_index=None):
        import jax
        devs = jax.local_devices()
        idx = device_index or 0
        if idx < len(devs):
            try:
                return devs[idx].memory_stats() or {}
            except Exception:
                return {}
        return {}

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True  # computed, not native — bf16 is the fast path

    def is_fp8_supported(self):
        # v5p+/v6 support fp8 matmuls; conservatively probe dtype availability
        import jax.numpy as jnp
        return hasattr(jnp, "float8_e4m3fn")

    def communication_backend_name(self):
        return self._communication_backend_name

    def op_builder_dir(self):
        return "deepspeed_tpu.ops.op_builder.tpu"

    @functools.lru_cache(None)
    def _builder_registry(self):
        from ..ops.op_builder import ALL_OPS
        return ALL_OPS

    def create_op_builder(self, class_name):
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name):
        return self._builder_registry().get(class_name)


class CPU_Accelerator(TPU_Accelerator):
    """Host-CPU accelerator (tests, offload targets). XLA:CPU backs compute."""

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "gloo"  # name parity; actual transport is XLA

    def is_available(self):
        return True

    def device_name(self, device_index=None):
        return "cpu"

    def is_bf16_supported(self):
        return True

    def is_fp8_supported(self):
        return False

    def op_builder_dir(self):
        return "deepspeed_tpu.ops.op_builder.cpu"
