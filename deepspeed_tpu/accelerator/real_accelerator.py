"""Accelerator auto-detection.

Analog of ``accelerator/real_accelerator.py:51`` (get_accelerator) with the
``DS_ACCELERATOR`` env override (reference ``:59``).
"""

import os

from ..utils.logging import logger

SUPPORTED_ACCELERATOR_LIST = ["tpu", "cpu"]

ds_accelerator = None


def _validate_accelerator(accel_name):
    if accel_name not in SUPPORTED_ACCELERATOR_LIST:
        raise ValueError(f"accelerator name {accel_name} not in supported list {SUPPORTED_ACCELERATOR_LIST}")


def is_current_accelerator_supported():
    return get_accelerator()._name in SUPPORTED_ACCELERATOR_LIST


def get_accelerator():
    global ds_accelerator
    if ds_accelerator is not None:
        return ds_accelerator

    accelerator_name = None
    if "DS_ACCELERATOR" in os.environ:
        accelerator_name = os.environ["DS_ACCELERATOR"]
        _validate_accelerator(accelerator_name)
    else:
        try:
            import jax
            platforms = {d.platform for d in jax.devices()}
            accelerator_name = "tpu" if "tpu" in platforms else "cpu"
        except Exception:
            accelerator_name = "cpu"

    from .tpu_accelerator import CPU_Accelerator, TPU_Accelerator
    if accelerator_name == "tpu":
        ds_accelerator = TPU_Accelerator()
    else:
        ds_accelerator = CPU_Accelerator()
    logger.info(f"Setting ds_accelerator to {ds_accelerator._name}")
    return ds_accelerator


def set_accelerator(accel_obj):
    global ds_accelerator
    ds_accelerator = accel_obj
    return ds_accelerator
