"""MoE public API.

Analog of ``deepspeed/moe/layer.py:17`` (MoE facade), ``experts.py:13``
(Experts), ``sharded_moe.py:449`` (TopKGate). The reference wraps a torch
expert module and dispatches via explicit ``_AllToAll``; here the facade owns
a functional param pytree whose "expert" logical axis shards over the
``expert`` mesh axis — the dispatch einsum lowers to the same all-to-all.
"""

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..models.config import TransformerConfig
from ..models import layers as L
from ..utils import groups
from .sharded_moe import top1_gating_einsum, topk_gating_einsum


class TopKGate:
    """Gating function holder (reference ``sharded_moe.py:449``)."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 8, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True, ep_group=None,
                 top2_2nd_expert_sampling: bool = True):
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.drop_tokens = drop_tokens

    def init(self, rng):
        return {"wg": (jax.random.normal(rng, (self.model_dim, self.num_experts),
                                         jnp.float32) * 0.02)}

    def __call__(self, params, tokens, train: bool = True):
        logits = tokens.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1_gating_einsum(logits, cf, self.min_capacity)
        return topk_gating_einsum(logits, self.k, cf, self.min_capacity)


class Experts:
    """Expert FFN bank (reference ``experts.py:13``): (X, E, F) stacked."""

    def __init__(self, model_dim: int, ffn_dim: int, num_experts: int,
                 activation: str = "swiglu"):
        self.model_dim = model_dim
        self.ffn_dim = ffn_dim
        self.num_experts = num_experts
        self.activation = activation

    def init(self, rng):
        r = jax.random.split(rng, 3)
        x, e, f = self.num_experts, self.model_dim, self.ffn_dim
        std = 0.02
        if self.activation == "swiglu":
            return {"wi_gate": jax.random.normal(r[0], (x, e, f)) * std,
                    "wi_up": jax.random.normal(r[1], (x, e, f)) * std,
                    "wo": jax.random.normal(r[2], (x, f, e)) * std}
        return {"wi": jax.random.normal(r[0], (x, e, f)) * std,
                "wo": jax.random.normal(r[2], (x, f, e)) * std}

    def __call__(self, params, expert_in):
        """expert_in: (X, C, E) → (X, C, E)."""
        if self.activation == "swiglu":
            g = jnp.einsum("xce,xef->xcf", expert_in, params["wi_gate"])
            u = jnp.einsum("xce,xef->xcf", expert_in, params["wi_up"])
            h = jax.nn.silu(g) * u
        else:
            h = jax.nn.gelu(jnp.einsum("xce,xef->xcf", expert_in, params["wi"]))
        return jnp.einsum("xcf,xfe->xce", h, params["wo"])


class MoE:
    """MoE facade (reference ``layer.py:17``): gate + experts + dispatch."""

    def __init__(self, hidden_size: int, expert=None, num_experts: int = 1,
                 ep_size: int = 1, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 use_residual: bool = False, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True,
                 ffn_dim: Optional[int] = None, activation: str = "swiglu",
                 enable_expert_tensor_parallelism: bool = False):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.use_residual = use_residual
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor,
                             eval_capacity_factor, min_capacity, noisy_gate_policy,
                             drop_tokens, use_rts)
        self.experts = expert or Experts(hidden_size, ffn_dim or 4 * hidden_size,
                                         num_experts, activation)

    def init(self, rng):
        r1, r2, r3 = jax.random.split(rng, 3)
        params = {"gate": self.gate.init(r1), "experts": self.experts.init(r2)}
        if self.use_residual:
            params["residual_mlp"] = Experts(self.hidden_size, self.hidden_size * 4, 1,
                                             "gelu").init(r3)
            params["coefficient"] = jax.random.normal(r3, (self.hidden_size, 2)) * 0.02
        return params

    def logical_axes(self):
        ax = {"gate": {"wg": ("embed", "unmodeled")},
              "experts": jax.tree.map(lambda _: ("expert", "embed", "mlp"),
                                      self.experts.init(jax.random.PRNGKey(0)))}
        # wo is (X, F, E)
        if "wo" in ax["experts"]:
            ax["experts"]["wo"] = ("expert", "mlp", "embed")
        return ax

    def __call__(self, params, hidden_states, train: bool = True):
        """hidden_states: (B, S, E) → (output (B, S, E), aux_loss, exp_counts)."""
        b, s, e = hidden_states.shape
        tokens = hidden_states.reshape(b * s, e)
        combine, dispatch, aux = self.gate(params["gate"], tokens, train)
        expert_in = jnp.einsum("txc,te->xce", dispatch.astype(tokens.dtype), tokens)
        expert_out = self.experts(params["experts"], expert_in)
        out = jnp.einsum("txc,xce->te", combine.astype(tokens.dtype), expert_out)
        out = out.reshape(b, s, e)
        if self.use_residual:
            res = Experts(self.hidden_size, self.hidden_size * 4, 1, "gelu")(
                params["residual_mlp"], tokens.reshape(1, b * s, e)).reshape(b, s, e)
            coef = jax.nn.softmax(hidden_states @ params["coefficient"], axis=-1)
            out = out * coef[..., 0:1] + res * coef[..., 1:2]
        exp_counts = jnp.sum(dispatch, axis=(0, 2))
        return out, aux, exp_counts


def split_params_into_different_moe_groups_for_optimizer(param_groups):
    """Reference ``moe/utils.py:72`` parity: tag expert params so ZeRO shards
    them over the expert-data group. With logical-axis sharding this is a
    no-op (expert axes are already mesh-mapped); kept for API compatibility."""
    return param_groups
