"""MoE gating and dispatch math.

TPU-native analog of ``deepspeed/moe/sharded_moe.py`` (top1gating ``:183``,
top2gating ``:290``, topkgating ``:374``, einsum dispatch/combine in
``MOELayer:96``). The reference dispatches tokens to expert-parallel ranks
with an explicit ``_AllToAll`` autograd op; here dispatch/combine are one-hot
einsums whose expert dim is sharded over the ``expert`` mesh axis, so XLA
lowers the same data movement to all-to-all over ICI.

All functions are capacity-based with static shapes (XLA requirement): each
expert processes exactly C = ceil(k*T/X * capacity_factor) token slots;
overflow tokens are dropped (their combine weight is 0), matching the
reference's ``drop_tokens=True`` default.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _capacity(num_tokens: int, num_experts: int, k: int, capacity_factor: float,
              min_capacity: int = 4) -> int:
    cap = int(num_tokens * k / num_experts * capacity_factor)
    cap = max(cap, min_capacity)
    # round to MXU-friendly multiple
    return ((cap + 7) // 8) * 8


def load_balancing_loss(gates, mask):
    """GShard aux loss: num_experts * Σ_e (fraction_tokens_e * mean_gate_e).

    gates: (T, X) softmax router probs; mask: (T, X) 0/1 top-k assignment.
    """
    num_experts = gates.shape[1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask.astype(jnp.float32), axis=0)
    return num_experts * jnp.sum(me * ce)


def topk_gating_einsum(logits, k: int = 2, capacity_factor: float = 1.25,
                       min_capacity: int = 4, normalize: bool = True
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k gating producing einsum dispatch/combine tensors.

    logits: (T, X) raw router outputs (fp32).
    ``normalize``: renormalize the k chosen gates to sum to 1 (Mixtral/top2
    convention); False keeps raw softmax mass (Qwen2-MoE norm_topk_prob=False).
    Returns (combine (T, X, C) fp32, dispatch (T, X, C) bool, aux_loss scalar).
    """
    t, x = logits.shape
    c = _capacity(t, x, k, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=-1)  # (T, X)

    # top-k expert choice per token
    topk_vals, topk_idx = jax.lax.top_k(gates, k)  # (T, k)
    if normalize:
        denom = jnp.sum(topk_vals, axis=-1, keepdims=True)
        topk_w = topk_vals / jnp.maximum(denom, 1e-9)
    else:
        topk_w = topk_vals

    # full assignment mask for aux loss
    mask_tx = jnp.sum(jax.nn.one_hot(topk_idx, x, dtype=jnp.float32), axis=1)  # (T, X)
    aux = load_balancing_loss(gates, mask_tx)

    # position of each (token, choice) within its expert's capacity buffer:
    # cumulative count over the flattened (choice-major, token) order, so
    # earlier tokens win slots — same priority rule as reference top2gating.
    onehot_kx = jax.nn.one_hot(topk_idx, x, dtype=jnp.int32)         # (T, k, X)
    flat = onehot_kx.transpose(1, 0, 2).reshape(k * t, x)            # (k*T, X)
    pos_in_expert = jnp.cumsum(flat, axis=0) - flat                  # (k*T, X)
    pos = jnp.sum(flat * pos_in_expert, axis=1).reshape(k, t).T      # (T, k)
    keep = pos < c                                                   # (T, k)

    w = topk_w * keep.astype(topk_w.dtype)                           # (T, k)
    # combine[t, x, c] = Σ_choice w[t,i] * [idx==x] * [pos==c]
    pos_oh = jax.nn.one_hot(pos, c, dtype=jnp.float32)               # (T, k, C)
    expert_oh = jax.nn.one_hot(topk_idx, x, dtype=jnp.float32)       # (T, k, X)
    combine = jnp.einsum("tk,tkx,tkc->txc", w.astype(jnp.float32), expert_oh, pos_oh)
    dispatch = combine > 0
    return combine, dispatch, aux


def topk_gating_grouped(logits, k: int = 2, normalize: bool = True):
    """Top-k gating for the grouped (megablox-style) dropless path.

    Returns (topk_idx (T, k) int32, weights (T, k) fp32 normalized over the
    k choices, aux_loss). No capacity buffers: every token reaches its
    experts (the reference's grouped MoE GEMM semantics,
    ``inference/v2/kernels/cutlass_ops/moe_gemm``).
    """
    x = logits.shape[1]
    gates = jax.nn.softmax(logits, axis=-1)
    topk_vals, topk_idx = jax.lax.top_k(gates, k)
    if normalize:
        denom = jnp.sum(topk_vals, axis=-1, keepdims=True)
        w = topk_vals / jnp.maximum(denom, 1e-9)
    else:
        w = topk_vals
    mask_tx = jnp.sum(jax.nn.one_hot(topk_idx, x, dtype=jnp.float32), axis=1)
    aux = load_balancing_loss(gates, mask_tx)
    return topk_idx.astype(jnp.int32), w.astype(jnp.float32), aux


def top1_gating_einsum(logits, capacity_factor: float = 1.0, min_capacity: int = 4):
    """Switch-style top-1 gating (reference ``top1gating:183``)."""
    return topk_gating_einsum(logits, k=1, capacity_factor=capacity_factor,
                              min_capacity=min_capacity)
