"""In-process elastic rejoin: survive membership changes WITHOUT restart.

Analog of the reference's elastic agent semantics
(``deepspeed/elasticity/elastic_agent.py:32``) taken one step further: the
reference (like torch-elastic) tears the worker processes down and respawns
them at the new world size; here the SURVIVING process itself rebuilds —
tear down the JAX distributed runtime, re-initialize at the remaining world
size, rebuild the mesh, reshard from the latest universal checkpoint
(``checkpoint/universal.py``), and keep training in the same PID.

Requirements baked into the flow:
- the initial bring-up must run with JAX recoverability on
  (``jax.config.jax_enable_recoverability`` — without it the coordination
  service hard-aborts every surviving process the moment a peer dies) and a
  short heartbeat timeout; ``comm.init_distributed(elastic=True)`` or
  ``InProcessElasticWorker.configure_jax()`` sets both;
- a universal checkpoint must exist from BEFORE the failure: a dead peer
  takes its ZeRO shards with it, so recovery rolls back to the last
  universal snapshot (standard elastic semantics — the reference's agent
  also resumes "from the latest checkpoint").

The liveness signal is deliberately simple and transport-free: per-rank
heartbeat files under a shared run directory (the launcher's shared-FS
contract). Anything smarter (coordination-service queries) couples recovery
to the very service that just lost a member.
"""

import json
import os
import time
from typing import Callable, List, Optional

from ..utils.logging import logger


class InProcessElasticWorker:
    """Membership tracking + in-process rebuild for one training process.

    ``make_engine(world_size) -> engine`` must build the full stack for the
    given world size from scratch (mesh from the then-visible devices, batch
    config from the elastic schedule) — it runs once at start and once per
    rejoin, AFTER the runtime has been torn down and re-initialized.
    """

    def __init__(self, make_engine: Callable[[int], object], ckpt_dir: str,
                 run_dir: str, heartbeat_timeout: float = 10.0):
        self.make_engine = make_engine
        self.ckpt_dir = ckpt_dir
        self.run_dir = run_dir
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.rank: Optional[int] = None
        self.world: Optional[int] = None
        self._epoch = 0
        os.makedirs(run_dir, exist_ok=True)

    # ---- liveness ----------------------------------------------------

    def _hb_path(self, rank: int) -> str:
        return os.path.join(self.run_dir, f"heartbeat.{rank}")

    @staticmethod
    def configure_jax(heartbeat_timeout_seconds: int = 5):
        """Must run BEFORE jax.distributed.initialize: recoverability keeps
        the coordination service from aborting survivors on peer death."""
        import jax
        jax.config.update("jax_enable_recoverability", True)
        os.environ.setdefault("DS_ELASTIC_HEARTBEAT_S",
                              str(heartbeat_timeout_seconds))

    def start(self, rank: int, world: int):
        self.rank = int(rank)
        self.world = int(world)
        if self.rank == 0:
            # leftover membership files from a previous incarnation of this
            # run_dir would be adopted as the current alive set; nobody reads
            # them until a failure, so cleaning at bring-up is race-free
            for epoch, path in self._membership_files().items():
                try:
                    os.remove(path)
                except OSError:
                    pass
        self.heartbeat()

    def heartbeat(self):
        path = self._hb_path(self.rank)
        with open(path, "w") as f:
            f.write(str(time.time()))

    def alive_ranks(self) -> List[int]:
        now = time.time()
        alive = []
        for r in range(self.world):
            try:
                if now - os.path.getmtime(self._hb_path(r)) <= self.heartbeat_timeout:
                    alive.append(r)
            except OSError:
                pass
        return alive

    def membership_changed(self) -> bool:
        return len(self.alive_ranks()) < self.world

    def _membership_files(self):
        out = {}
        try:
            names = os.listdir(self.run_dir)
        except OSError:
            return out
        for fn in names:
            if fn.startswith("membership.") and not fn.count(".tmp"):
                try:
                    out[int(fn.split(".", 1)[1])] = os.path.join(self.run_dir, fn)
                except ValueError:
                    pass
        return out

    def _agree_alive(self) -> List[int]:
        """Survivors must agree on ONE alive set before re-initializing: a
        heartbeat mtime that straddles the timeout at the moment each
        survivor looks would otherwise yield different
        (num_processes, process_id) arguments and hang/abort the rebuilt
        world (advisor r4). Every survivor waits a settle delay (lets
        straddling mtimes resolve), re-reads, and the one that then believes
        itself lowest-alive publishes its set to the next epoch's file with
        O_EXCL — FIRST writer wins, so even if two survivors self-elect
        (they disagreed about each other's liveness), everyone re-reads the
        single published file and adopts the same set. The epoch is
        discovered by scanning, not counted blindly, so a survivor that
        coalesced two failures into one rejoin stays in sync."""
        baseline = self._epoch
        time.sleep(min(1.0, self.heartbeat_timeout / 4))
        self.heartbeat()
        alive = self.alive_ranks()

        def newest_published():
            # any epoch past our last consumed one counts — a survivor that
            # detected the failure late must adopt the set the leader has
            # ALREADY published, not wait on a self-computed future epoch
            files = {e: p for e, p in self._membership_files().items()
                     if e > baseline}
            for e in sorted(files, reverse=True):
                try:
                    with open(files[e]) as f:
                        return e, json.loads(f.read())
                except (OSError, ValueError):
                    continue    # mid-write; a lower epoch or retry covers it
            return None, None

        epoch, published = newest_published()
        if published is None and alive and self.rank == min(alive):
            epoch = max(self._membership_files().keys() | {baseline}) + 1
            path = os.path.join(self.run_dir, f"membership.{epoch}")
            try:
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
                with os.fdopen(fd, "w") as f:
                    f.write(json.dumps(alive))
            except FileExistsError:
                pass      # another self-elected survivor won the publish
        deadline = time.time() + 2 * self.heartbeat_timeout
        while time.time() < deadline:
            epoch, published = newest_published()
            if published is not None:
                self._epoch = epoch
                return published
            time.sleep(0.1)
        # leader died between detection and publish: fall back to own view
        logger.warning("[elastic-rejoin] no membership published after epoch "
                       f"{baseline}; using local view {alive}")
        self._epoch = baseline + 1
        return alive

    # ---- checkpoint --------------------------------------------------

    def save_universal(self, engine):
        """Periodic world-size-agnostic snapshot — the recovery point."""
        from ..checkpoint.universal import ds_to_universal
        ds_to_universal(engine, self.ckpt_dir)

    # ---- the rejoin itself -------------------------------------------

    def rejoin(self):
        """Tear down the distributed runtime, come back at the surviving
        world size, reshard from the universal checkpoint. Returns the new
        engine; the old one (and every array it held) is invalid after this.
        """
        import jax

        # refresh own liveness first: a survivor whose heartbeat went stale
        # (blocked in a long step) must not drop out of its own alive set —
        # that would collapse new_rank to 0 on several survivors at once
        self.heartbeat()
        alive = self._agree_alive()
        new_world = max(1, len(alive))
        logger.warning(
            f"[elastic-rejoin] membership change: {self.world} -> {new_world} "
            f"processes (alive ranks {alive}); rebuilding in-process")

        from ..comm import comm as dist
        from ..utils import groups
        try:
            dist.destroy_process_group()
        except Exception as e:  # a failed shutdown barrier is EXPECTED here
            logger.warning(f"[elastic-rejoin] destroy_process_group: {e}")
        try:
            jax.distributed.shutdown()
        except Exception as e:
            logger.warning(f"[elastic-rejoin] jax.distributed.shutdown: {e}")
        jax.clear_caches()
        from jax.extend import backend as jax_backend
        jax_backend.clear_backends()
        groups.reset_mesh()

        # new rank = position among survivors; re-rendezvous only if >1 left
        new_rank = alive.index(self.rank) if self.rank in alive else 0
        os.environ["RANK"] = str(new_rank)
        os.environ["WORLD_SIZE"] = str(new_world)
        if new_world > 1:
            jax.distributed.initialize(
                num_processes=new_world, process_id=new_rank,
                heartbeat_timeout_seconds=int(
                    os.environ.get("DS_ELASTIC_HEARTBEAT_S", "5")))

        self.rank, self.world = new_rank, new_world
        engine = self.make_engine(new_world)
        from ..checkpoint.universal import load_universal_checkpoint
        meta = load_universal_checkpoint(engine, self.ckpt_dir)
        self.heartbeat()
        logger.warning(
            f"[elastic-rejoin] resumed at world={new_world} from "
            f"global_step={meta.get('global_steps', 0)}")
        return engine
