"""Elastic training configuration math.

Analog of ``deepspeed/elasticity/elasticity.py`` (``compute_elastic_config:
233``, candidate batch/GPU math ``:27-126``): precompute batch sizes valid
across a range of accelerator counts so scaling events keep
batch-size-sensitive hyperparameters fixed. Pure math — identical semantics.
"""

from typing import Dict, List, Tuple

from ..utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def get_candidate_batch_sizes(base_list: List[int], max_acceptable_batch_size: int) -> List[int]:
    """All batch sizes b = base * 2^k ≤ max, deduped ascending (ref ``:27``)."""
    candidates = set()
    for base in base_list:
        b = base
        while b <= max_acceptable_batch_size:
            candidates.add(b)
            b *= 2
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int,
                   max_valid_gpus: int) -> List[int]:
    """GPU counts g where batch_size % (g * mb) == 0 for some micro batch
    (ref ``:44``)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_gpus = batch_size // mb
        for g in range(1, max_gpus + 1):
            if batch_size % (g * mb) == 0 and min_valid_gpus <= g <= max_valid_gpus:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes: List[int], micro_batches: List[int],
                        min_gpus: int, max_gpus: int, prefer_larger: bool):
    """(batch, valid_gpus) maximizing GPU-count coverage (ref ``:63``)."""
    max_valid = 0
    best_batch = None
    best_gpus = []
    for batch in candidate_batch_sizes:
        gpus = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        if len(gpus) > max_valid or (len(gpus) == max_valid and prefer_larger and
                                     best_batch is not None and batch > best_batch):
            max_valid = len(gpus)
            best_batch = batch
            best_gpus = gpus
    return best_batch, best_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size, min_gpus=1,
                             max_gpus=10000, prefer_larger=True):
    candidates = get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference ``:233``: resolve final batch config from the elasticity block."""
    elastic = ds_config.get("elasticity")
    if elastic is None:
        raise ElasticityConfigError("'elasticity' block missing from config")
    if not elastic.get("enabled", False):
        raise ElasticityConfigError("elasticity.enabled is false")
    micro_batches = elastic.get("micro_batch_sizes", [])
    max_batch = elastic.get("max_train_batch_size", 0)
    min_gpus = elastic.get("min_gpus", 1)
    max_gpus = elastic.get("max_gpus", 10000)
    prefer_larger = elastic.get("prefer_larger_batch", True)
    if not micro_batches or max_batch <= 0:
        raise ElasticityConfigError("micro_batch_sizes and max_train_batch_size required")

    final_batch, valid_gpus = _get_compatible_gpus_v01(
        micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)
    if final_batch is None:
        raise ElasticityConfigError("no compatible batch size found")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus}")
        mb = None
        order = sorted(micro_batches, reverse=prefer_larger)
        for candidate in order:
            if final_batch % (world_size * candidate) == 0:
                mb = candidate
                break
        if return_microbatch:
            return final_batch, valid_gpus, mb
        return final_batch, valid_gpus

    if return_microbatch:
        return final_batch, valid_gpus, None
    return final_batch, valid_gpus
