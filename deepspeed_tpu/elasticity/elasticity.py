"""Elastic training configuration math.

Analog of ``deepspeed/elasticity/elasticity.py`` (``compute_elastic_config:
233``, candidate batch/GPU math ``:27-126``): precompute batch sizes valid
across a range of accelerator counts so scaling events keep
batch-size-sensitive hyperparameters fixed.

Semantics match the reference: candidate global batch sizes are each base
(every micro-batch size plus their LCM) scaled by the largest highly
composite number that keeps the product under the acceptable maximum —
HCNs maximize the divisor count, i.e. the number of compatible device
counts. Valid device counts are the divisors of batch/micro_batch within
[min, max]. v0.2 additionally works at node granularity with a model
parallel degree (``_get_compatible_gpus_v02``, reference ``:129``).
The HCN table is generated, not transcribed.
"""

import math
from functools import lru_cache, reduce
from typing import Dict, List, Tuple

from ..utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def _divisor_count(n: int) -> int:
    c = 1
    d = 2
    while d * d <= n:
        if n % d == 0:
            e = 0
            while n % d == 0:
                n //= d
                e += 1
            c *= e + 1
        d += 1
    if n > 1:
        c *= 2
    return c


@lru_cache(maxsize=1)
def _hcn_list(limit: int = 750_000) -> Tuple[int, ...]:
    """Highly composite numbers ≤ limit (record-setting divisor counts).

    Every HCN is a product of the first k primes with non-increasing
    exponents, so enumerating that family and keeping divisor-count records
    reproduces the sequence without a full scan."""
    primes = (2, 3, 5, 7, 11, 13, 17)

    def gen(i, value, max_exp, out):
        out.append(value)
        if i == len(primes):
            return
        p = primes[i]
        v = value
        for e in range(1, max_exp + 1):
            v *= p
            if v > limit:
                break
            gen(i + 1, v, e, out)

    family: List[int] = []
    gen(0, 1, 40, family)
    records = []
    best = 0
    for n in sorted(set(family)):
        c = _divisor_count(n)
        if c > best:
            best = c
            records.append(n)
    return tuple(records)


def _largest_hcn_at_most(value: int) -> int:
    hcns = _hcn_list()
    best = 1
    for h in hcns:
        if h > value:
            break
        best = h
    return best


def get_candidate_batch_sizes(base_list: List[int], max_acceptable_batch_size: int) -> List[int]:
    """For each base, the largest base × HCN ≤ max (reference ``:27``)."""
    candidates = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidates.add(base)
        else:
            candidates.add(base * _largest_hcn_at_most(max_acceptable_batch_size // base))
    out = sorted(candidates)
    logger.info(f"Candidate batch sizes: {out}")
    return out


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int,
                   max_valid_gpus: int) -> List[int]:
    """Device counts g dividing batch/mb for some micro batch — i.e. the
    divisors of each quotient, bounded to [min, max] (reference ``:41``)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        q = batch_size // mb
        d = 1
        while d * d <= q:
            if q % d == 0:
                for g in (d, q // d):
                    if min_valid_gpus <= g <= max_valid_gpus:
                        valid.add(g)
            d += 1
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes: List[int], micro_batches: List[int],
                        min_gpus: int, max_gpus: int, prefer_larger: bool):
    """(batch, valid_gpus) maximizing device-count coverage, batch size as
    the tie-break in the preferred direction (reference ``:63``)."""
    max_valid = 0
    best_batch = min(micro_batches)
    best_gpus = None
    for batch in candidate_batch_sizes:
        gpus = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better_tie = (prefer_larger and batch > best_batch) or \
                     (not prefer_larger and batch < best_batch)
        if len(gpus) > max_valid or (len(gpus) == max_valid and better_tie):
            max_valid = len(gpus)
            best_batch = batch
            best_gpus = gpus
    return best_batch, best_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size, min_gpus=None,
                             max_gpus=None, prefer_larger=True):
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ElasticityConfigError(
            f"every micro batch must be <= max_acceptable_batch_size="
            f"{max_acceptable_batch_size}")
    lcm = reduce(math.lcm, micro_batches)
    base_list = list(micro_batches) + [lcm]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def _get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size, current_num_gpus,
                             min_gpus=None, max_gpus=None, prefer_larger=True,
                             num_gpus_per_node=1, model_parallel_size=1):
    """Node-granular variant with model parallelism (reference ``:129``):
    elasticity counts nodes, each contributing num_gpus_per_node /
    model_parallel_size data-parallel ranks."""
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(
            f"num_gpus_per_node={num_gpus_per_node} must be divisible by "
            f"model_parallel_size={model_parallel_size}")
    dp_per_node = num_gpus_per_node // model_parallel_size

    batch, valid_nodes = _get_compatible_gpus_v01(
        micro_batches, int(max_acceptable_batch_size / dp_per_node),
        int((min_gpus or 1) / num_gpus_per_node) or 1,
        int((max_gpus or current_num_gpus) / num_gpus_per_node) or 1,
        prefer_larger=prefer_larger)
    final_batch = int(batch) * dp_per_node
    valid_dp = [n * dp_per_node for n in (valid_nodes or [])]

    def pick_micro(fb):
        chosen = None
        for mb in micro_batches:
            if (fb // max(current_num_gpus, 1)) % mb == 0:
                if chosen is None or (prefer_larger and mb > chosen):
                    chosen = mb
        return chosen

    if current_num_gpus // model_parallel_size in valid_dp:
        return final_batch, valid_dp, pick_micro(final_batch)
    raise ElasticityIncompatibleWorldSize(
        f"current world {current_num_gpus} (mp={model_parallel_size}) not in "
        f"valid data-parallel set {valid_dp}")


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference ``:233``: resolve final batch config from the elasticity block."""
    elastic = ds_config.get("elasticity")
    if elastic is None:
        raise ElasticityConfigError("'elasticity' block missing from config")
    if not elastic.get("enabled", False):
        raise ElasticityConfigError("elasticity.enabled is false")
    micro_batches = elastic.get("micro_batch_sizes", [])
    max_batch = elastic.get("max_train_batch_size", 0)
    min_gpus = elastic.get("min_gpus", 1)
    max_gpus = elastic.get("max_gpus", 10000)
    prefer_larger = elastic.get("prefer_larger_batch", True)
    version = float(elastic.get("version", 0.1))
    if not micro_batches or max_batch <= 0:
        raise ElasticityConfigError("micro_batch_sizes and max_train_batch_size required")

    if version >= 0.2 and world_size > 0:
        final_batch, valid_gpus, mb = _get_compatible_gpus_v02(
            micro_batches, max_batch, world_size, min_gpus, max_gpus, prefer_larger,
            num_gpus_per_node=elastic.get("num_gpus_per_node", 1),
            model_parallel_size=elastic.get("model_parallel_size", 1))
        if return_microbatch:
            return final_batch, valid_gpus, mb
        return final_batch, valid_gpus

    final_batch, valid_gpus = _get_compatible_gpus_v01(
        micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)
    if final_batch is None:
        raise ElasticityConfigError("no compatible batch size found")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus}")
        mb = None
        order = sorted(micro_batches, reverse=prefer_larger)
        for candidate in order:
            if final_batch % (world_size * candidate) == 0:
                mb = candidate
                break
        if return_microbatch:
            return final_batch, valid_gpus, mb
        return final_batch, valid_gpus

    if return_microbatch:
        return final_batch, valid_gpus, None
    return final_batch, valid_gpus
