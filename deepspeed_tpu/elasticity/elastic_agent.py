"""Elastic training agent (restart-based supervision).

Analog of ``deepspeed/elasticity/elastic_agent.py:32`` (DSElasticAgent, an
extension of torch-elastic's LocalElasticAgent): supervise the worker
group, and when workers die — or the node set changes — restart them at a
world size the precomputed elastic batch configuration admits, resuming
from the latest checkpoint. Torch-elastic's rendezvous is replaced by the
launcher's hostfile contract: ``jax.distributed.initialize`` performs the
actual process-group bring-up on restart.

For recovery WITHOUT a process restart — surviving workers tear down the
distributed runtime in place, rebuild the mesh at the remaining world size,
and reshard from a universal checkpoint — see ``elasticity/rejoin.py``
(InProcessElasticWorker).
"""

import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from ..utils.logging import logger
from .elasticity import ElasticityIncompatibleWorldSize, compute_elastic_config


class WorkerSpec:
    """What to (re)launch: argv builder parameterized by world size."""

    def __init__(self, cmd_for_world: Callable[[int], List[str]],
                 env: Optional[Dict[str, str]] = None):
        self.cmd_for_world = cmd_for_world
        self.env = env


class ElasticAgent:
    """Restart loop with elastic world-size renegotiation.

    ``available_nodes_fn`` reports currently healthy device counts (on a
    TPU pod slice: live hosts × chips per host); after a worker failure the
    agent drops to the largest valid world size ≤ what is available and
    relaunches. ``max_restarts`` bounds the loop (reference torch-elastic
    semantics); a clean exit ends it.
    """

    def __init__(self, ds_config: Dict, spec: WorkerSpec,
                 available_nodes_fn: Callable[[], int],
                 max_restarts: int = 3, backoff_s: float = 5.0):
        self.ds_config = ds_config
        self.spec = spec
        self.available_nodes_fn = available_nodes_fn
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.restart_count = 0

    def _negotiate_world_size(self) -> int:
        available = int(self.available_nodes_fn())
        _, valid_gpus = compute_elastic_config(self.ds_config)
        fits = [g for g in valid_gpus if g <= available]
        if not fits:
            raise ElasticityIncompatibleWorldSize(
                f"no valid world size <= available {available} in {valid_gpus}")
        return max(fits)

    def run(self) -> int:
        while True:
            world = self._negotiate_world_size()
            cmd = self.spec.cmd_for_world(world)
            logger.info(f"[elastic-agent] launching world_size={world}: {cmd}")
            proc = subprocess.Popen(cmd, env=self.spec.env)
            rc = proc.wait()
            if rc == 0:
                return 0
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                logger.error(f"[elastic-agent] giving up after "
                             f"{self.max_restarts} restarts (last rc={rc})")
                return rc
            logger.warning(f"[elastic-agent] worker group failed rc={rc}; "
                           f"restart {self.restart_count}/{self.max_restarts} "
                           f"in {self.backoff_s}s")
            time.sleep(self.backoff_s)


def cli_main(argv=None):
    """``ds_elastic`` analog: inspect/validate an elastic config."""
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="Show compatible world sizes for an elastic DeepSpeed config")
    parser.add_argument("-c", "--config", required=True, help="ds_config json path")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="validate this world size against the config")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)
    if args.world_size:
        batch, valid, mb = compute_elastic_config(ds_config, world_size=args.world_size,
                                                  return_microbatch=True)
        print(f"world size: {args.world_size}")
        print(f"final train_batch_size: {batch}")
        print(f"micro_batch_per_gpu: {mb}")
    else:
        batch, valid = compute_elastic_config(ds_config)
        print(f"final train_batch_size: {batch}")
        print(f"valid world sizes: {valid}")
    return 0
