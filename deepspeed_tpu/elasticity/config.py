"""Elasticity config. Analog of ``deepspeed/elasticity/config.py``."""


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityConfig:
    """Controls elastic batch-size/device-count co-design.

    {
      "elasticity": {
        "enabled": true,
        "max_train_batch_size": 2000,
        "micro_batch_sizes": [2,4,6],
        "min_gpus": 1, "max_gpus": 10000,
        "min_time": 20,
        "prefer_larger_batch": true,
        "ignore_non_elastic_batch_info": false,
        "version": 0.1
      }
    }
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get("enabled", False)
        if "max_train_batch_size" in param_dict:
            self.max_acceptable_batch_size = param_dict["max_train_batch_size"]
        else:
            raise ElasticityConfigError("Elasticity config missing max_train_batch_size")
        if "micro_batch_sizes" in param_dict:
            self.micro_batches = param_dict["micro_batch_sizes"]
        else:
            raise ElasticityConfigError("Elasticity config missing micro_batch_sizes")
        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"Elasticity expected value of micro_batch_sizes to be a list of micro batches, "
                f"instead is: {type(self.micro_batches)}, containing: {self.micro_batches}")
        if not all(isinstance(m, int) for m in self.micro_batches):
            raise ElasticityConfigError(f"Elasticity expected micro_batch_sizes to only contain ints, "
                                        f"instead contains: {self.micro_batches}")
        if not all(m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(f"Elasticity expected micro_batch_sizes to only contain positive ints, "
                                        f"instead contains: {self.micro_batches}")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", 10000)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError("Elasticity min/max gpus must be > 0, "
                                        f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError("Elasticity min_gpus cannot be greater than max_gpus, "
                                        f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}")
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)
        if self.model_parallel_size < 1:
            raise ElasticityConfigError("Model-Parallel size cannot be less than 1, "
                                        f"given model-parallel size: {self.model_parallel_size}")
        self.num_gpus_per_node = param_dict.get("num_gpus_per_node", 1)
        if self.num_gpus_per_node < 1:
            raise ElasticityConfigError("Number of GPUs per node cannot be less than 1, "
                                        f"given number of GPUs per node: {self.num_gpus_per_node}")
        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", LATEST_ELASTICITY_VERSION)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get("ignore_non_elastic_batch_info", False)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json_repr(self.__dict__)


def json_repr(d):
    import json
    return json.dumps(d, indent=2, default=str)
