"""Experiment monitoring fan-out.

Analog of ``deepspeed/monitor/monitor.py:30`` (MonitorMaster → TensorBoard /
W&B / CSV / Comet). Events are ``(tag, value, step)`` triples written from
rank 0 only.
"""

import csv
import os
from typing import List, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.config = config
        self.enabled = getattr(config, "enabled", False)

    def write_events(self, events: List[Event]):
        raise NotImplementedError


class CSVMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.enabled = config.enabled and jax.process_index() == 0
        if self.enabled:
            self.output_path = config.output_path or "./csv_monitor"
            self.job_name = config.job_name
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            fname = os.path.join(self.output_path, self.job_name,
                                 tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, float(value)])


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.enabled = False
        if config.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                path = os.path.join(config.output_path or "./tensorboard", config.job_name)
                self.writer = SummaryWriter(log_dir=path)
                self.enabled = True
            except Exception as e:
                logger.warning(f"TensorBoard unavailable: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self.writer.add_scalar(tag, float(value), step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.enabled = False
        if config.enabled and jax.process_index() == 0:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group, entity=config.team)
                self.wandb = wandb
                self.enabled = True
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self.wandb.log({tag: float(value)}, step=step)


class CometMonitor(Monitor):
    """Comet ML fan-out (reference ``monitor/comet.py``); import-gated the
    same way as W&B — absence of the SDK degrades to disabled, not error."""

    def __init__(self, config):
        super().__init__(config)
        self.enabled = False
        if config.enabled and jax.process_index() == 0:
            try:
                import comet_ml
                self.experiment = comet_ml.Experiment(
                    api_key=config.api_key, project_name=config.project,
                    workspace=config.workspace)
                if config.experiment_name:
                    self.experiment.set_name(config.experiment_name)
                self.enabled = True
            except Exception as e:
                logger.warning(f"comet_ml unavailable: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self.experiment.log_metric(tag, float(value), step=step)


class MonitorMaster(Monitor):
    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.monitors = []
        if monitor_config is None:
            self.enabled = False
            return
        if monitor_config.tensorboard.enabled:
            self.monitors.append(TensorBoardMonitor(monitor_config.tensorboard))
        if monitor_config.csv_monitor.enabled:
            self.monitors.append(CSVMonitor(monitor_config.csv_monitor))
        if monitor_config.wandb.enabled:
            self.monitors.append(WandbMonitor(monitor_config.wandb))
        if monitor_config.comet.enabled:
            self.monitors.append(CometMonitor(monitor_config.comet))
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, events: List[Event]):
        for m in self.monitors:
            m.write_events(events)
