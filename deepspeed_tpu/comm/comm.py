"""Module-level communication API.

Analog of ``deepspeed/comm/comm.py``: module-level collectives + init, with
the ``timed_op`` profiling wrapper and ``log_summary`` (reference
``comm/comm.py:101,422``). Backed by :class:`XlaBackend` (eager, host-level)
— in-trace code should use the functions re-exported from ``backend`` (psum,
all_gather, ...) inside ``shard_map``.
"""

import functools
import os
import time
from typing import Optional

import jax

from ..utils import groups
from ..utils.logging import logger
from .backend import ReduceOp, XlaBackend
from .backend import (all_gather, all_to_all, pmax, pmean, ppermute, psum,  # noqa: F401 (in-trace API)
                      psum_scatter, ring_send_recv)

cdb: Optional[XlaBackend] = None  # "communication data backend" — name kept from reference
comms_logger = None
timers = None


class CommsConfig:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.debug = False
        self.prof_all = True
        self.prof_ops = []


class CommsLogger:
    """Records per-op counts/sizes/latencies. Analog of utils/comms_logging.py."""

    def __init__(self):
        self.comms_dict = {}
        self.verbose = False
        self.debug = False
        self.prof_ops = []
        self.prof_all = True
        self.enabled = False

    def configure(self, comms_config):
        self.enabled = comms_config.enabled
        if self.enabled:
            self.verbose = comms_config.verbose
            self.debug = comms_config.debug
            self.prof_ops = comms_config.prof_ops
            self.prof_all = comms_config.prof_all

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def append(self, raw_name, record_name, latency, msg_size):
        algbw = (msg_size / latency) / 1e9 if latency > 0 else 0.0
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                self.comms_dict[record_name][msg_size][0] += 1
                self.comms_dict[record_name][msg_size][1].append(latency)
                self.comms_dict[record_name][msg_size][2].append(algbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, [latency], [algbw]]
        else:
            self.comms_dict[record_name] = {msg_size: [1, [latency], [algbw]]}
        if self.verbose:
            logger.info(f"comm op: {record_name} | time (ms): {latency * 1000:.2f} | msg size: {msg_size} | "
                        f"algbw (GB/s): {algbw:.2f}")

    def log_all(self, print_log=True, show_straggler=False):
        import numpy as np
        output = ["Comm. Op    Message Size    Count    Total Latency(ms)    Avg Latency(ms)    algbw(GB/s)"]
        for record_name in self.comms_dict:
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count, latencies, algbws = vals
                output.append(f"{record_name:<12}{msg_size:<16}{count:<9}{sum(latencies)*1000:<21.2f}"
                              f"{np.mean(latencies)*1000:<19.2f}{np.mean(algbws):<.2f}")
        text = "\n".join(output)
        if print_log:
            logger.info("\n" + text)
        return text


def _msg_size(tensor):
    try:
        return tensor.size * tensor.dtype.itemsize
    except Exception:
        return 0


def timed_op(func):
    """Wrap an eager collective with wall-clock + message-size profiling."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        global comms_logger
        prof = comms_logger is not None and comms_logger.enabled and (comms_logger.prof_all
                                                                      or func.__name__ in comms_logger.prof_ops)
        if not prof:
            return func(*args, **kwargs)
        tensor = args[0] if args else kwargs.get("tensor")
        start = time.perf_counter()
        result = func(*args, **kwargs)
        jax.block_until_ready(result) if result is not None else None
        latency = time.perf_counter() - start
        comms_logger.append(func.__name__, func.__name__, latency, _msg_size(tensor))
        return result

    return wrapper


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1,
                     mesh_config=None,
                     elastic=False):
    """Bring up the (multi-host) runtime and the global device mesh.

    Analog of ``deepspeed/comm/comm.py:619``. Single-host: no-op rendezvous.
    Multi-host: ``jax.distributed.initialize`` (TPU pods auto-discover via the
    metadata server, so coordinator args are optional there).

    ``elastic=True`` brings the runtime up recoverable (survivors are NOT
    aborted when a peer dies) with a short failure-detection heartbeat —
    required for in-process rejoin (``elasticity/rejoin.py``).
    """
    global cdb, comms_logger
    if elastic:
        from ..elasticity.rejoin import InProcessElasticWorker
        InProcessElasticWorker.configure_jax()
    if cdb is not None and cdb.initialized:
        # comm backend persists across engines in one process; the mesh may
        # still need (re)building from this config (e.g. a MiCS/hpZ zrep split)
        if not groups.mesh_is_initialized():
            groups.set_mesh(groups.build_mesh(mesh_config=mesh_config))
        return cdb
    cdb = XlaBackend()

    # Decide multi-process bring-up from env/args ONLY: touching
    # jax.process_count()/jax.devices() here would initialize the XLA backend
    # and make the subsequent jax.distributed.initialize() fail.
    coordinator = os.environ.get("MASTER_ADDR")
    n_proc = int(os.environ.get("WORLD_SIZE", world_size if world_size > 0 else 1))
    proc_id = int(os.environ.get("RANK", rank if rank >= 0 else 0))
    if n_proc > 1:
        addr = f"{coordinator}:{distributed_port}" if coordinator else None
        cdb.init_process_group(coordinator_address=addr, num_processes=n_proc, process_id=proc_id)
    else:
        cdb.init_process_group()

    if not groups.mesh_is_initialized():
        groups.set_mesh(groups.build_mesh(mesh_config=mesh_config))
    if comms_logger is None:
        comms_logger = CommsLogger()
    if verbose:
        mesh = groups.get_mesh()
        logger.info(f"Initialized distributed: processes={jax.process_count()} devices={jax.device_count()} "
                    f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}")
    return cdb


def initialize_mesh_device(mesh_shape, mesh_axis_names=None):
    """Analog of ``comm/comm.py:603`` — explicit mesh construction."""
    import numpy as np
    from jax.sharding import Mesh
    devices = np.asarray(jax.devices()).reshape(mesh_shape)
    mesh = Mesh(devices, mesh_axis_names or groups.MESH_AXIS_ORDER[:len(mesh_shape)])
    groups.set_mesh(mesh)
    return mesh


def is_initialized():
    return cdb is not None and cdb.initialized


def _ensure_backend():
    global cdb
    if cdb is None or not cdb.initialized:
        init_distributed(verbose=False)
    return cdb


def get_rank(group=None):
    return _ensure_backend().rank()


def get_world_size(group=None):
    if group is not None:
        import math
        mesh = groups.get_mesh()
        axes = (group,) if isinstance(group, str) else tuple(group)
        return math.prod(mesh.shape[a] for a in axes)
    return jax.device_count()


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", 0))


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    global comms_logger
    if comms_logger is None:
        comms_logger = CommsLogger()
    cfg = CommsConfig()
    if deepspeed_config is not None:
        cl = deepspeed_config.comms_logger
        cfg.enabled, cfg.verbose, cfg.prof_all, cfg.debug, cfg.prof_ops = (cl.enabled, cl.verbose, cl.prof_all,
                                                                           cl.debug, cl.prof_ops)
    for name, val in (("enabled", enabled), ("prof_all", prof_all), ("prof_ops", prof_ops), ("verbose", verbose),
                      ("debug", debug)):
        if val is not None:
            setattr(cfg, name, val)
    comms_logger.configure(cfg)


def log_summary(show_straggler=False):
    global comms_logger
    if comms_logger is not None:
        return comms_logger.log_all(show_straggler=show_straggler)


# ---- eager collectives (host-level / benchmarking) ----

class CommHandle:
    """Async work handle (reference async_op=True contract). XLA dispatch is
    already asynchronous, so the collective is in flight the moment the
    handle exists; ``wait()`` blocks until the result is materialized and
    returns it. Coalesced placeholders resolve on manager exit."""

    def __init__(self, result=None):
        self._result = result

    def _set(self, result):
        self._result = result

    def wait(self):
        import jax
        if self._result is None:
            raise RuntimeError("handle not resolved — still inside an open "
                               "coalescing_manager block?")
        jax.block_until_ready(self._result)
        return self._result

    def is_completed(self):
        if self._result is None:
            return False
        try:
            return self._result.is_ready()
        except AttributeError:
            return True

    @property
    def result(self):
        return self.wait()


class _Coalescer:
    """Batches collectives issued inside ``coalescing_manager`` into one
    flat call per (kind, op) — the reference TorchBackend coalescing
    manager (``comm/torch.py:41``) / ZeRO's allgather bucket analog."""

    def __init__(self, group):
        self.group = group
        self.pending = []   # (kind, op, tensor, handle)

    def add(self, kind, op, tensor):
        h = CommHandle()
        self.pending.append((kind, op, tensor, h))
        return h

    def flush(self):
        import jax.numpy as jnp
        from collections import defaultdict
        groups_ = defaultdict(list)
        for kind, op, tensor, h in self.pending:
            groups_[(kind, op, tensor.dtype)].append((tensor, h))
        for (kind, op, _dtype), items in groups_.items():
            tensors = [t.reshape(-1) for t, _ in items]
            sizes = [t.size for t in tensors]
            flat = jnp.concatenate(tensors)
            if kind == "all_reduce":
                out = _ensure_backend().all_reduce(flat, op=op, group=self.group)
                outs = jnp.split(out, list(_np_cumsum(sizes)[:-1]))
                for (t, h), o in zip(items, outs):
                    h._set(o.reshape(t.shape))
            elif kind == "all_gather":
                out = _ensure_backend().all_gather_into_tensor(flat, group=self.group)
                n = out.shape[0] // flat.shape[0]
                per_rank = out.reshape(n, flat.shape[0])
                offs = _np_cumsum(sizes)
                start = 0
                for (t, h), end in zip(items, offs):
                    # same contract as the direct call: dim-0-tiled original
                    h._set(per_rank[:, start:end].reshape(
                        (n * t.shape[0],) + tuple(t.shape[1:])))
                    start = end
            else:
                raise NotImplementedError(kind)
        self.pending.clear()


def _np_cumsum(sizes):
    import numpy as _np
    return _np.cumsum(sizes)


_ACTIVE_COALESCER = None


def coalescing_manager(group=None, async_op=True):
    """Context manager: collectives issued inside are batched into one flat
    exchange per (kind, op) on exit; each call returns a ``CommHandle`` that
    resolves after the flush (reference ``comm/torch.py:41``)."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        global _ACTIVE_COALESCER
        prev = _ACTIVE_COALESCER
        _ACTIVE_COALESCER = _Coalescer(group)
        try:
            yield _ACTIVE_COALESCER
            _ACTIVE_COALESCER.flush()
        finally:
            _ACTIVE_COALESCER = prev

    return cm()


def _maybe_handle(result, async_op):
    return CommHandle(result) if async_op else result


@timed_op
def all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False):
    if _ACTIVE_COALESCER is not None:
        return _ACTIVE_COALESCER.add("all_reduce", op, tensor)
    return _maybe_handle(_ensure_backend().all_reduce(tensor, op=op, group=group),
                         async_op)


@timed_op
def all_gather_into_tensor(tensor, group=None, async_op=False):
    if _ACTIVE_COALESCER is not None:
        return _ACTIVE_COALESCER.add("all_gather", None, tensor)
    return _maybe_handle(
        _ensure_backend().all_gather_into_tensor(tensor, group=group), async_op)


@timed_op
def reduce_scatter_tensor(tensor, op=ReduceOp.SUM, group=None, async_op=False):
    return _maybe_handle(
        _ensure_backend().reduce_scatter_tensor(tensor, op=op, group=group),
        async_op)


@timed_op
def all_to_all_single(tensor, scatter_dim=0, gather_dim=0, group=None, async_op=False):
    return _maybe_handle(_ensure_backend().all_to_all_single(
        tensor, scatter_dim=scatter_dim, gather_dim=gather_dim, group=group), async_op)


@timed_op
def broadcast(tensor, src=0, group=None, async_op=False):
    return _maybe_handle(_ensure_backend().broadcast(tensor, src=src, group=group),
                         async_op)


def barrier(group=None):
    _ensure_backend().barrier(group=group)


def destroy_process_group():
    global cdb
    if cdb is not None:
        cdb.destroy_process_group()
        cdb = None
