"""Communication backends.

Analog of ``deepspeed/comm/backend.py:25`` (Backend ABC) + ``comm/torch.py:90``
(TorchBackend). On TPU the "backend" is XLA itself: collectives are
``jax.lax`` primitives compiled into the step and scheduled onto ICI/DCN by the
runtime, so the backend's job is (a) process bring-up (``jax.distributed``),
(b) exposing eager collectives for host-level control flow (barriers, scalar
consensus, benchmarking) by jitting ``shard_map`` wrappers over the mesh, and
(c) tagging in-trace collectives for the comms logger.
"""

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import groups
from ..utils.logging import logger


def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
    """Version-portable shard_map (jax>=0.8 moved it to jax.shard_map)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_rep)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_rep)


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"
    BAND = "band"
    BOR = "bor"
    BXOR = "bxor"


def _lax_reduce(op, x, axis_name):
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis_name)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axis_name)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    if op in (ReduceOp.PROD, ReduceOp.BAND, ReduceOp.BOR, ReduceOp.BXOR):
        # No native XLA collective: gather the n shards (n static) and fold.
        import functools as ft
        gathered = jax.lax.all_gather(x, axis_name, axis=0, tiled=False)
        if op == ReduceOp.PROD:
            return jnp.prod(gathered, axis=0)
        fold = {ReduceOp.BAND: jnp.bitwise_and, ReduceOp.BOR: jnp.bitwise_or,
                ReduceOp.BXOR: jnp.bitwise_xor}[op]
        return ft.reduce(fold, [gathered[i] for i in range(gathered.shape[0])])
    raise ValueError(f"Unsupported reduce op: {op}")


def _normalize_group(group) -> tuple:
    """group may be None (all data-like axes), an axis name, or a tuple of axis names."""
    if group is None:
        return tuple(a for a in groups.MESH_AXIS_ORDER if groups.get_mesh().shape[a] > 1) or ("data",)
    if isinstance(group, str):
        return (group,)
    return tuple(group)


class XlaBackend:
    """Eager collectives over the global mesh, compiled once per (shape, op).

    These exist for host-level control flow and benchmarking; hot-loop
    collectives should live inside the user's jitted step where XLA fuses and
    schedules them.
    """

    name = "xla"

    def __init__(self):
        self._initialized = False
        self._collective_cache = {}

    def init_process_group(self, coordinator_address=None, num_processes=None, process_id=None):
        if self._initialized:
            return
        if num_processes is not None and num_processes > 1:
            # Must run before ANY jax call that touches the XLA backend
            # (callers must not query jax.devices()/process_count() first).
            kw = {}
            hb = os.environ.get("DS_ELASTIC_HEARTBEAT_S")
            if hb:   # elastic bring-up: fast failure detection
                kw["heartbeat_timeout_seconds"] = int(hb)
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kw,
            )
        self._initialized = True

    @property
    def initialized(self):
        return self._initialized

    def rank(self):
        return jax.process_index()

    def size(self):
        return jax.process_count()

    def device_count(self):
        return jax.device_count()

    # -- eager collectives (operate on mesh-sharded arrays) --

    def _make_collective(self, kind, axis_names, op, ndim, scatter_dim=0, gather_dim=0):
        mesh = groups.get_mesh()
        key = (mesh, kind, axis_names, op, ndim, scatter_dim, gather_dim)
        cached = self._collective_cache.get(key)
        if cached is not None:
            return cached
        axis = axis_names if len(axis_names) > 1 else axis_names[0]
        full = P(*([None] * ndim))

        if kind == "all_reduce":
            in_spec = out_spec = full

            def fn(x):
                return _lax_reduce(op, x, axis)
        elif kind == "all_gather":
            in_spec = P(axis_names, *([None] * (ndim - 1)))
            out_spec = full

            def fn(x):
                return jax.lax.all_gather(x, axis, axis=0, tiled=True)
        elif kind == "reduce_scatter":
            in_spec = full
            out_spec = P(axis_names, *([None] * (ndim - 1)))

            def fn(x):
                return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        elif kind == "all_to_all":
            in_spec = P(axis_names, *([None] * (ndim - 1)))
            out_spec = P(axis_names, *([None] * (ndim - 1)))

            def fn(x):
                return jax.lax.all_to_all(x, axis, split_axis=scatter_dim, concat_axis=gather_dim, tiled=True)
        elif kind == "broadcast":
            in_spec = out_spec = full

            def fn(x):
                # replicate rank-0's copy: select index 0 along the axis
                idx = jax.lax.axis_index(axis)
                return jax.lax.psum(jnp.where(idx == 0, x, jnp.zeros_like(x)), axis)
        else:
            raise ValueError(kind)

        smapped = shard_map(fn, mesh, (in_spec,), out_spec, check_rep=False)
        jitted = jax.jit(smapped)
        if len(self._collective_cache) > 512:
            self._collective_cache.clear()
        self._collective_cache[key] = jitted
        return jitted

    def all_reduce(self, tensor, op=ReduceOp.SUM, group=None):
        axes = _normalize_group(group)
        return self._make_collective("all_reduce", axes, op, tensor.ndim)(tensor)

    def all_gather_into_tensor(self, tensor, group=None):
        axes = _normalize_group(group)
        return self._make_collective("all_gather", axes, ReduceOp.SUM, tensor.ndim)(tensor)

    def reduce_scatter_tensor(self, tensor, op=ReduceOp.SUM, group=None):
        axes = _normalize_group(group)
        return self._make_collective("reduce_scatter", axes, op, tensor.ndim)(tensor)

    def all_to_all_single(self, tensor, scatter_dim=0, gather_dim=0, group=None):
        axes = _normalize_group(group)
        return self._make_collective("all_to_all", axes, ReduceOp.SUM, tensor.ndim, scatter_dim,
                                     gather_dim)(tensor)

    def broadcast(self, tensor, src=0, group=None):
        if src != 0:
            raise NotImplementedError("eager broadcast supports src=0 (mesh-major rank) only")
        axes = _normalize_group(group)
        return self._make_collective("broadcast", axes, ReduceOp.SUM, tensor.ndim)(tensor)

    def barrier(self, group=None):
        # A tiny allreduce forces a rendezvous across all participants.
        x = jnp.ones((1,), dtype=jnp.int32)
        jax.block_until_ready(self.all_reduce(x, ReduceOp.SUM, group))

    def destroy_process_group(self):
        self._initialized = False
        self._collective_cache.clear()


# In-trace collective functions — usable inside shard_map'd code. These are the
# hot-path API: thin, traced, fused by XLA.

def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def psum_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def ring_send_recv(x, axis_name, shift=1):
    """Send to rank+shift, receive from rank-shift along a ring (pipeline p2p analog)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)
