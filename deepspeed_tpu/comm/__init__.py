from .backend import ReduceOp, XlaBackend, shard_map
from .comm import (CommHandle, all_gather, all_gather_into_tensor, all_reduce, all_to_all, all_to_all_single,
                   barrier, broadcast, coalescing_manager, configure, destroy_process_group, get_local_rank,
                   get_rank, get_world_size, init_distributed, initialize_mesh_device, is_initialized,
                   log_summary, pmax, pmean, ppermute, psum, psum_scatter, reduce_scatter_tensor,
                   ring_send_recv, timed_op)
