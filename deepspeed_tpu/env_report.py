"""Environment / op-compatibility report.

Analog of ``deepspeed/env_report.py:183`` (``ds_report`` CLI): prints
platform, jax/runtime versions, device inventory, and per-op build/compat
status from the op-builder registry.
"""

import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{YELLOW}[NO]{END}"


def op_report(verbose=False):
    from .ops.op_builder import ALL_OPS
    lines = ["-" * 74,
             "op name" + " " * 23 + "kind" + " " * 12 + "compatible",
             "-" * 74]
    for name, cls in sorted(ALL_OPS.items()):
        b = cls()
        kind = "pallas" if "Pallas" in type(b).__mro__[1].__name__ else "native"
        ok = b.is_compatible(verbose=verbose)
        lines.append(f"{b.name:<30}{kind:<16}{OKAY if ok else NO}"
                     + (f"  {b.error_log}" if (not ok and b.error_log) else ""))
    return "\n".join(lines)


def env_info():
    import jax
    lines = ["-" * 74, "DeepSpeed-TPU general environment info:", "-" * 74]
    import deepspeed_tpu
    lines.append(f"deepspeed_tpu version ....... {deepspeed_tpu.__version__}")
    lines.append(f"python version .............. {sys.version.split()[0]}")
    lines.append(f"jax version ................. {jax.__version__}")
    try:
        import jaxlib
        lines.append(f"jaxlib version .............. {jaxlib.__version__}")
    except Exception:
        pass
    lines.append(f"default backend ............. {jax.default_backend()}")
    try:
        devs = jax.devices()
        lines.append(f"devices ..................... {len(devs)} x {devs[0].device_kind}")
        mems = {m.kind for m in devs[0].addressable_memories()}
        lines.append(f"memory spaces ............... {sorted(mems)}")
    except Exception as e:
        lines.append(f"devices ..................... unavailable ({e})")
    for mod in ("flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            lines.append(f"{mod:<28}. {getattr(m, '__version__', '?')}")
        except Exception:
            lines.append(f"{mod:<28}. not installed")
    return "\n".join(lines)


def main(verbose=True):
    print(op_report(verbose=False))
    print(env_info())
    return 0


def cli_main():
    sys.exit(main())


if __name__ == "__main__":
    cli_main()
