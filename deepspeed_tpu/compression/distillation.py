"""Knowledge-distillation hooks for compression training.

Analog of the reference's distillation stage (``init_compression``'s
``teacher_model`` + the kd-loss term the compression tutorials wire into the
training loop; XTC's recipe prescribes a distillation phase after layer
reduction/binarization). TPU-native shape: the student model is WRAPPED —
its ``loss`` becomes ``(1 - alpha) * CE + alpha * T^2 * KL(teacher || student)``
— so ZeRO/offload/bf16 engine features compose without engine changes.
(The pipeline engine drives ``head_loss`` directly and does not carry the
KD term; distill under DP/ZeRO, as the reference tutorials do.)

Teacher logits enter through the BATCH (``batch["teacher_logits"]``), not a
closed-over teacher forward: closed-over device arrays get baked into the
compiled step as constants (the tunnel rejects multi-MB programs), and
batch-borne logits let the teacher run anywhere — a separate jit on the
same chip (``make_teacher_provider``), a different host, or offline
precomputation over the dataset (the cheapest classic KD setup).
"""

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


def kd_loss(student_logits, teacher_logits, temperature: float = 1.0,
            loss_mask=None):
    """Soft-target KL: T^2 * mean_t KL(softmax(t/T) || softmax(s/T)).
    ``loss_mask`` weights positions exactly like the CE term (pad/prompt
    tokens must not pull the student toward the teacher)."""
    t = jnp.asarray(temperature, jnp.float32)
    sl = student_logits.astype(jnp.float32) / t
    tl = teacher_logits.astype(jnp.float32) / t
    p_t = jax.nn.softmax(tl, axis=-1)
    kl = jnp.sum(p_t * (jax.nn.log_softmax(tl, axis=-1)
                        - jax.nn.log_softmax(sl, axis=-1)), axis=-1)
    if loss_mask is None:
        return (t * t) * jnp.mean(kl)
    m = loss_mask.astype(jnp.float32)
    return (t * t) * jnp.sum(kl * m) / jnp.maximum(jnp.sum(m), 1.0)


class DistilledModel:
    """Student wrapper adding the KD term to the loss.

    ``alpha`` mixes hard CE and soft KD; ``temperature`` softens both
    distributions. Batches WITHOUT ``teacher_logits`` fall back to the plain
    student loss (so eval/serving paths are untouched).
    """

    def __init__(self, student, alpha: float = 0.5, temperature: float = 2.0):
        self.student = student
        self.alpha = float(alpha)
        self.temperature = float(temperature)

    @classmethod
    def from_config(cls, student, ds_config: Dict[str, Any]):
        kd = (ds_config.get("compression_training", {})
              .get("knowledge_distillation", {}))
        if not kd.get("enabled", False):
            return student
        return cls(student, alpha=kd.get("alpha", 0.5),
                   temperature=kd.get("temperature", 2.0))

    # engine protocol: delegate everything except loss
    def __getattr__(self, name):
        return getattr(self.student, name)

    def loss(self, params, batch):
        teacher_logits = batch.get("teacher_logits")
        if teacher_logits is None:
            return self.student.loss(params, batch)
        # ONE student forward serves both terms: logit distillation needs
        # the dense logits anyway, so CE is derived from them (+ the MoE
        # router aux the plain loss would carry) instead of a second pass
        from ..models.transformer import masked_token_nll
        s_logits, aux = self.student.apply(
            params, batch["input_ids"], positions=batch.get("positions"),
            segment_ids=batch.get("segment_ids"), return_aux_loss=True)
        ce = masked_token_nll(s_logits, batch["labels"],
                              batch.get("loss_mask"))
        cfg = self.student.cfg
        if cfg.is_moe:
            ce = ce + cfg.moe_aux_loss_coef * aux
        kd = kd_loss(s_logits, teacher_logits, self.temperature,
                     loss_mask=batch.get("loss_mask"))
        return (1.0 - self.alpha) * ce + self.alpha * kd


def make_teacher_provider(teacher_model, teacher_params,
                          ) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Returns ``augment(batch) -> batch + {"teacher_logits"}``: one jitted
    teacher forward per batch, run OUTSIDE the training step (its output is
    then just another staged batch leaf)."""
    fwd = jax.jit(lambda p, ids: teacher_model.apply(p, ids))

    def augment(batch):
        out = dict(batch)
        out["teacher_logits"] = fwd(teacher_params, batch["input_ids"])
        return out

    return augment
