"""Compression technique scheduler.

Analog of ``deepspeed/compression/scheduler.py:12`` (compression_scheduler):
each technique in the compression config carries a ``schedule_offset``;
during training the scheduler tracks steps and activates techniques as
their offsets pass. The reference flips flags on injected modules; here the
scheduler returns/applies the functional transforms from ``compress.py``
for whichever techniques are currently live, so the training loop applies
compression as a pure param transformation at technique boundaries.

Usage::

    sched = CompressionScheduler(ds_config)
    for batch in loader:
        newly = sched.step()              # techniques that just activated
        if newly:
            params = sched.apply(engine.module_params)
            engine.module_params = params
        engine.train_batch(batch)
"""

from typing import Dict, List

from ..utils.logging import logger
from .compress import _apply_to_params, fake_quantize, magnitude_prune

WEIGHT_QUANTIZATION = "weight_quantization"
SPARSE_PRUNING = "sparse_pruning"

_TECHNIQUES = (WEIGHT_QUANTIZATION, SPARSE_PRUNING)


class CompressionScheduler:
    def __init__(self, deepspeed_config: Dict):
        self.config = deepspeed_config.get("compression_training", {})
        self.training_steps = 0
        self._active = {t: False for t in _TECHNIQUES}

    def _offset(self, technique: str) -> int:
        shared = self.config.get(technique, {}).get("shared_parameters", {})
        return int(shared.get("schedule_offset", 0))

    def _enabled(self, technique: str) -> bool:
        shared = self.config.get(technique, {}).get("shared_parameters", {})
        return bool(shared.get("enabled", False))

    def active_techniques(self) -> List[str]:
        return [t for t, on in self._active.items() if on]

    def step(self, steps: int = 1) -> List[str]:
        """Advance the step count; returns techniques that JUST activated
        (reference check_* methods flipping enabled flags at offset)."""
        self.training_steps += steps
        newly = []
        for t in _TECHNIQUES:
            if (self._enabled(t) and not self._active[t]
                    and self.training_steps >= self._offset(t)):
                self._active[t] = True
                newly.append(t)
                logger.info(f"compression: {t} enabled at step {self.training_steps}")
        return newly

    def apply(self, params):
        """Apply the currently-active techniques' transforms to ``params``."""
        if self._active[WEIGHT_QUANTIZATION]:
            for gname, g in self.config[WEIGHT_QUANTIZATION].get(
                    "different_groups", {}).items():
                bits = g.get("params", {}).get("start_bits", 8)
                mods = g.get("modules", ["attn", "mlp"])
                params = _apply_to_params(
                    params, lambda w: fake_quantize(w, int(bits)), mods)
        if self._active[SPARSE_PRUNING]:
            for gname, g in self.config[SPARSE_PRUNING].get(
                    "different_groups", {}).items():
                dense = float(g.get("params", {}).get("dense_ratio", 0.5))
                mods = g.get("modules", ["mlp"])
                params = _apply_to_params(
                    params, lambda w: magnitude_prune(w, 1.0 - dense), mods)
        return params
