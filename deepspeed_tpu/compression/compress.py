"""Model compression entry points.

Analog of ``deepspeed/compression/compress.py`` (init_compression /
redundancy_clean) + ``basic_layer.py`` quant/prune modules: config-driven
weight quantization (QAT fake-quant), magnitude pruning, and layer reduction
applied to a native param pytree.
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def fake_quantize(w, bits: int = 8, symmetric: bool = True):
    """Quantization-aware fake-quant (reference QuantAct/LinearLayer_Compress):
    round-trip through the integer grid, straight-through in backward.
    ``bits=1`` binarizes and ``bits=2`` ternarizes (the XTC extreme-
    compression grid, reference ``basic_layer.py`` Binary/TernaryQuantizer)."""
    if bits == 1:
        return binarize(w)
    if bits == 2:
        return ternarize(w)
    qmax = 2 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(w)), 1e-10) / qmax
    q = jnp.clip(jnp.round(w / scale), -qmax, qmax)
    deq = q * scale
    # straight-through estimator: identity gradient
    return w + jax.lax.stop_gradient(deq - w)


def binarize(w):
    """XTC 1-bit weights: sign(w) scaled by the per-output-channel mean
    magnitude (reference BinaryQuantizer / BWN), straight-through backward."""
    axis = tuple(range(w.ndim - 1)) if w.ndim > 1 else None
    scale = jnp.mean(jnp.abs(w), axis=axis, keepdims=w.ndim > 1)
    deq = jnp.sign(jnp.where(w == 0, 1.0, w)) * scale
    return w + jax.lax.stop_gradient(deq - w)


def ternarize(w):
    """XTC 2-bit (ternary) weights: {-a, 0, +a} with the TWN threshold
    0.7 * mean|w| and a = mean magnitude of the surviving weights
    (reference TernaryQuantizer), straight-through backward."""
    axis = tuple(range(w.ndim - 1)) if w.ndim > 1 else None
    thr = 0.7 * jnp.mean(jnp.abs(w), axis=axis, keepdims=w.ndim > 1)
    mask = (jnp.abs(w) > thr).astype(w.dtype)
    denom = jnp.maximum(jnp.sum(mask, axis=axis, keepdims=w.ndim > 1), 1.0)
    a = jnp.sum(jnp.abs(w) * mask, axis=axis, keepdims=w.ndim > 1) / denom
    deq = jnp.sign(w) * mask * a
    return w + jax.lax.stop_gradient(deq - w)


def fake_quantize_activation(x, bits: int = 8):
    """Activation fake-quant (reference QuantAct): dynamic symmetric
    per-tensor scale from the running batch, straight-through backward.
    Used by models with ``act_quant_bits`` set (QAT for W+A quantization)."""
    qmax = 2 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(jax.lax.stop_gradient(x))), 1e-10) / qmax
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax) * scale
    return x + jax.lax.stop_gradient(q - x)


def magnitude_prune(w, sparsity: float):
    """Zero the smallest-|w| fraction (reference SparsePruning_Compress)."""
    if sparsity <= 0.0:
        return w
    k = int(w.size * sparsity)
    if k == 0:
        return w
    threshold = jnp.sort(jnp.abs(w).reshape(-1))[k - 1]
    return jnp.where(jnp.abs(w) > threshold, w, 0.0)


def head_prune(w_heads, num_keep: int):
    """Prune attention heads by L2 norm; w_heads: (E, H, D) or (H, D, E)."""
    axis = 1 if w_heads.shape[0] > w_heads.shape[1] else 0
    norms = jnp.sqrt(jnp.sum(jnp.square(w_heads), axis=tuple(
        i for i in range(w_heads.ndim) if i != axis)))
    keep = jnp.sort(jnp.argsort(norms)[-num_keep:])
    mask = jnp.zeros((w_heads.shape[axis],)).at[keep].set(1.0)
    shape = [1] * w_heads.ndim
    shape[axis] = -1
    return w_heads * mask.reshape(shape)


def _mlp_channel_norms(mlp):
    """Per-intermediate-channel L2 norm of the block's input weights —
    (…, F) for (…, E, F) weights; gated MLPs sum gate+up contributions."""
    parts = [mlp[k] for k in ("wi", "wi_gate", "wi_up") if k in mlp]
    sq = sum(jnp.sum(jnp.square(p.astype(jnp.float32)), axis=-2)
             for p in parts)
    return jnp.sqrt(sq)


def row_prune_mlp(mlp, dense_ratio: float, dim_reduction: bool = False):
    """Structured row/channel pruning of one MLP block (reference
    ``compression/basic_layer.py:166 enable_row_pruning`` + ``:212
    fix_row_col_pruning_helper``): the intermediate channels with the
    smallest input-weight norms are pruned — the producing weights' OUTPUT
    rows and the consuming ``wo``'s INPUT rows together, so the block's
    function only loses the dropped channels.

    ``dim_reduction=False`` (training): channels are MASKED to zero, shapes
    unchanged — the QAT-style stage. ``dim_reduction=True``
    (redundancy_clean): weights are physically SLICED to F' =
    round(F * dense_ratio); the caller serves/trains the result under a
    config with the reduced intermediate size. Works on stacked (L, E, F)
    layer trees (per-layer channel choice) and single blocks.
    """
    f = mlp["wo"].shape[-2]
    k = max(1, int(round(f * float(dense_ratio))))
    norms = _mlp_channel_norms(mlp)                       # (..., F)
    keep = jnp.sort(jnp.argsort(norms, axis=-1)[..., f - k:], axis=-1)

    def take_last(w):     # gather along the last (channel) dim
        idx = jnp.broadcast_to(keep[..., None, :], w.shape[:-1] + (k,))
        return jnp.take_along_axis(w, idx.astype(jnp.int32), axis=-1)

    def take_rows(w):     # gather wo's input (second-to-last) dim
        idx = jnp.broadcast_to(keep[..., :, None], w.shape[:-2] + (k, w.shape[-1]))
        return jnp.take_along_axis(w, idx.astype(jnp.int32), axis=-2)

    new = dict(mlp)
    if dim_reduction:
        for key in ("wi", "wi_gate", "wi_up"):
            if key in new:
                new[key] = take_last(new[key])
        if "bi" in new:
            new["bi"] = jnp.take_along_axis(new["bi"], keep.astype(jnp.int32),
                                            axis=-1)
        new["wo"] = take_rows(new["wo"])
        return new
    mask = jax.nn.one_hot(keep, f, dtype=mlp["wo"].dtype).sum(axis=-2)
    for key in ("wi", "wi_gate", "wi_up"):
        if key in new:
            new[key] = new[key] * mask[..., None, :]
    if "bi" in new:
        new["bi"] = new["bi"] * mask
    new["wo"] = new["wo"] * mask[..., :, None]
    return new


def _map_mlps(tree, fn, patterns=None, prefix=""):
    """Apply ``fn`` to every MLP block ({wi|wi_gate, wo} dict) whose dotted
    path matches one of ``patterns`` (None = every block)."""
    if isinstance(tree, dict):
        if "wo" in tree and ("wi" in tree or "wi_gate" in tree):
            if patterns is None or _match(prefix[:-1], patterns):
                return fn(tree)
            return tree
        return {k: _map_mlps(v, fn, patterns, f"{prefix}{k}.")
                for k, v in tree.items()}
    return tree


def _match(path: str, patterns):
    return any(p in path for p in patterns)


def _apply_to_params(params, fn, patterns, prefix=""):
    if isinstance(params, dict):
        return {k: _apply_to_params(v, fn, patterns, f"{prefix}{k}.")
                for k, v in params.items()}
    if _match(prefix[:-1], patterns):
        return fn(params)
    return params


def init_compression(model_or_params, deepspeed_config: Dict, teacher_model=None,
                     mpu=None):
    """Apply the compression config to a param pytree (reference
    init_compression). Returns transformed params.

    Distillation is a MODEL transform, not a param transform: wrap the
    student with ``distillation.DistilledModel.from_config`` (the
    ``knowledge_distillation`` config block) and feed batches through
    ``make_teacher_provider``. Activation QAT is a model-config switch
    (``act_quant_bits``)."""
    params = model_or_params
    comp = deepspeed_config.get("compression_training", {})

    wq = comp.get("weight_quantization", {}).get("shared_parameters", {})
    if wq.get("enabled", False):
        groups_cfg = comp["weight_quantization"].get("different_groups", {})
        for gname, g in groups_cfg.items():
            bits = g.get("params", {}).get("start_bits", 8)
            mods = g.get("modules", ["attn", "mlp"])
            params = _apply_to_params(params, lambda w: fake_quantize(w, int(bits)), mods)
            logger.info(f"compression: fake-quant {bits}b on {mods}")

    sp = comp.get("sparse_pruning", {}).get("shared_parameters", {})
    if sp.get("enabled", False):
        groups_cfg = comp["sparse_pruning"].get("different_groups", {})
        for gname, g in groups_cfg.items():
            dense_ratio = g.get("params", {}).get("dense_ratio", 0.5)
            mods = g.get("modules", ["mlp"])
            params = _apply_to_params(
                params, lambda w: magnitude_prune(w, 1.0 - float(dense_ratio)), mods)
            logger.info(f"compression: pruning to dense_ratio={dense_ratio} on {mods}")

    rp = comp.get("row_pruning", {}).get("shared_parameters", {})
    if rp.get("enabled", False):
        # training stage: channels masked, shapes unchanged (reference
        # enable_row_pruning); redundancy_clean does the dim reduction
        for gname, g in comp["row_pruning"].get("different_groups", {}).items():
            dense_ratio = float(g.get("params", {}).get("dense_ratio", 0.5))
            mods = g.get("modules")      # None = every MLP block
            params = _map_mlps(params,
                               lambda m: row_prune_mlp(m, dense_ratio), mods)
            logger.info(f"compression: row pruning (masked) to "
                        f"dense_ratio={dense_ratio} on {mods or 'all MLPs'}")
    return params


def redundancy_clean(model_or_params, deepspeed_config: Dict, mpu=None):
    """Reference redundancy_clean: make training-time compression PHYSICAL —
    layer reduction slices the stacked layer dim; row pruning slices the
    masked intermediate channels out of every MLP (the
    ``fix_row_col_pruning_helper(dim_reduction=True)`` analog) — serve the
    result under a config with the matching reduced intermediate size."""
    params = model_or_params
    comp = deepspeed_config.get("compression_training", {})
    rp = comp.get("row_pruning", {}).get("shared_parameters", {})
    if rp.get("enabled", False):
        for gname, g in comp["row_pruning"].get("different_groups", {}).items():
            dense_ratio = float(g.get("params", {}).get("dense_ratio", 0.5))
            mods = g.get("modules")
            params = _map_mlps(params, lambda m: row_prune_mlp(
                m, dense_ratio, dim_reduction=True), mods)
            logger.info(f"row pruning: dims reduced to "
                        f"dense_ratio={dense_ratio} on {mods or 'all MLPs'}")
    lr_cfg = deepspeed_config.get("compression_training", {}).get("layer_reduction", {})
    if not lr_cfg.get("enabled", False):
        return params
    keep = lr_cfg.get("keep_layers")
    import re
    if isinstance(params.get("layers"), dict) and params["layers"] and \
            all(re.fullmatch(r"g\d+", k) for k in params["layers"]):
        raise NotImplementedError(
            "layer reduction over heterogeneous (grouped) layer stacks is "
            "ambiguous — reduce before grouping or use a homogeneous model")
    if keep is None:
        n = lr_cfg.get("keep_number_layer")
        total = jax.tree.leaves(params["layers"])[0].shape[0]
        keep = list(range(0, total, max(1, total // n)))[:n]
    keep_idx = jnp.asarray(keep)
    params = dict(params)
    params["layers"] = jax.tree.map(lambda x: x[keep_idx], params["layers"])
    logger.info(f"layer reduction: kept layers {list(keep)}")
    return params


# ---- named recipes -------------------------------------------------------

# Reference recipe presets (docs/blogs: XTC extreme compression = layer
# reduction + binarized weights + distillation stage; ZeroQuant = fine-
# grained W8/W4 group quantization). Returned dicts are plain compression
# configs for init_compression / CompressionScheduler — start points users
# tune, mirroring the reference's config_templates.

def xtc_recipe(keep_number_layer=6, start_bits=1, schedule_offset=2000,
               kd_alpha=0.7, kd_temperature=2.0):
    """Extreme compression (XTC): deep layer reduction + binarized (1-bit)
    weights + a knowledge-distillation stage (the reference XTC pipeline:
    reduce, binarize past the offset, distill from the uncompressed
    teacher)."""
    return {"compression_training": {
        "layer_reduction": {"enabled": True,
                            "keep_number_layer": keep_number_layer},
        "weight_quantization": {
            "shared_parameters": {"enabled": True,
                                  "schedule_offset": schedule_offset},
            "different_groups": {"xtc_w": {"params": {"start_bits": start_bits},
                                           "modules": ["attn", "mlp"]}}},
        "knowledge_distillation": {"enabled": True, "alpha": kd_alpha,
                                   "temperature": kd_temperature},
    }}


def zeroquant_recipe(weight_bits=8, schedule_offset=0):
    """ZeroQuant-style post-training quantization: W8 (or W4) group quant on
    every projection; activations stay in compute dtype (bf16 on TPU)."""
    return {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True,
                                  "schedule_offset": schedule_offset},
            "different_groups": {
                "zq_attn": {"params": {"start_bits": weight_bits},
                            "modules": ["attn"]},
                "zq_mlp": {"params": {"start_bits": weight_bits},
                           "modules": ["mlp"]}}},
    }}
