"""Checkpoint-file loading for inference: serve without a live torch model.

Analog of ``deepspeed/module_inject/load_checkpoint.py`` +
``inference/engine.py:444`` (sharded-checkpoint loading): the reference
accepts ``init_inference(checkpoint=...)`` pointing at sharded weight files
(or a JSON manifest listing them) so multi-hundred-GB models never need a
fully materialized torch module. Here the same surface is a **lazy mapping**
over HF-layout checkpoint directories — ``model.safetensors`` (single or
index-sharded) or ``pytorch_model.bin`` (single or index-sharded) — that the
declarative containers (``inference/v2/model_implementations/archs.py``)
consume tensor-by-tensor: peak host memory is one shard (torch) or one
tensor (safetensors), not the model.
"""

import json
import os
from collections import OrderedDict
from collections.abc import Mapping
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import logger


class CheckpointStateDict(Mapping):
    """Lazy name→tensor mapping over sharded checkpoint files.

    safetensors shards are read tensor-at-a-time (zero-copy slices); torch
    ``.bin``/``.pt`` shards are deserialized whole and held in a 2-shard LRU
    — containers walk layers in order and HF shards are name-contiguous, so
    two slots absorb boundary straddles while peak host memory stays at two
    shards, not the model (the point of serving from files). bf16 tensors
    are upcast to fp32 on the way out (numpy has no bf16; the container
    casts to the serving dtype anyway).
    """

    _LRU_SHARDS = 2

    def __init__(self, weight_map: Dict[str, str]):
        # weight_map: tensor name → absolute file path
        self._map = dict(weight_map)
        self._torch_cache: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._st_cache: "OrderedDict[str, Any]" = OrderedDict()

    @classmethod
    def from_files(cls, paths: List[str]) -> "CheckpointStateDict":
        """Build the name→file map by enumerating each file ONCE (torch
        shards loaded for enumeration stay in the LRU for the first reads)."""
        sd = cls({})
        for p in paths:
            for name in sd._names_in(p):
                sd._map[name] = p
        return sd

    def _load_shard(self, path):
        if path in self._torch_cache:
            self._torch_cache.move_to_end(path)
        else:
            import torch
            self._torch_cache[path] = torch.load(
                path, map_location="cpu", weights_only=True)
            while len(self._torch_cache) > self._LRU_SHARDS:
                self._torch_cache.popitem(last=False)
        return self._torch_cache[path]

    def _open_st(self, path):
        """Cached safe_open handle: per-tensor reads without reparsing the
        shard header on every access (same LRU policy as torch shards)."""
        if path in self._st_cache:
            self._st_cache.move_to_end(path)
        else:
            from safetensors import safe_open
            self._st_cache[path] = safe_open(path, framework="pt")
            while len(self._st_cache) > self._LRU_SHARDS:
                self._st_cache.popitem(last=False)
        return self._st_cache[path]

    def _names_in(self, path) -> List[str]:
        if path.endswith(".safetensors"):
            return list(self._open_st(path).keys())
        return list(self._load_shard(path).keys())

    # -- Mapping interface (what Param.materialize/build_params need) --

    def __contains__(self, name):
        return name in self._map

    def __iter__(self):
        return iter(self._map)

    def __len__(self):
        return len(self._map)

    def __getitem__(self, name):
        path = self._map[name]
        if path.endswith(".safetensors"):
            t = self._open_st(path).get_tensor(name)
        else:
            t = self._load_shard(path)[name]
        import torch
        if t.dtype == torch.bfloat16:   # numpy cannot represent bf16
            t = t.to(torch.float32)
        return t


_INDEX_FILES = ("model.safetensors.index.json", "pytorch_model.bin.index.json")
_SINGLE_FILES = ("model.safetensors", "pytorch_model.bin")


def load_checkpoint_state_dict(checkpoint) -> Tuple[CheckpointStateDict, Optional[str]]:
    """Resolve a checkpoint spec → (lazy state dict, directory or None).

    Accepted forms (reference ``inference/engine.py:444``):
    - a directory in HF layout (index-sharded or single-file);
    - a single weight file path;
    - a JSON manifest path or dict with a ``checkpoints`` file list
      (paths relative to the manifest's directory, or absolute).
    """
    base: Optional[str] = None
    if isinstance(checkpoint, str) and os.path.isdir(checkpoint):
        base = checkpoint
        for idx in _INDEX_FILES:
            p = os.path.join(base, idx)
            if os.path.exists(p):
                with open(p) as f:
                    wm = json.load(f)["weight_map"]
                return CheckpointStateDict(
                    {k: os.path.join(base, v) for k, v in wm.items()}), base
        for single in _SINGLE_FILES:
            p = os.path.join(base, single)
            if os.path.exists(p):
                return CheckpointStateDict.from_files([p]), base
        raise FileNotFoundError(
            f"no checkpoint weights found under {base!r} "
            f"(looked for {_INDEX_FILES + _SINGLE_FILES})")

    if isinstance(checkpoint, str) and checkpoint.endswith(".json"):
        base = os.path.dirname(os.path.abspath(checkpoint))
        with open(checkpoint) as f:
            checkpoint = json.load(f)

    if isinstance(checkpoint, dict):
        files = checkpoint.get("checkpoints") or checkpoint.get("checkpoint_files")
        if not files:
            raise ValueError(
                "checkpoint manifest must list files under 'checkpoints'")
        if isinstance(files, str):
            files = [files]
        # an explicit base_path always wins (same semantics whether the
        # manifest arrived as a file or a dict)
        base = checkpoint.get("base_path", base)
        if base is None and any(not os.path.isabs(f) for f in files):
            raise ValueError(
                "manifest passed as a dict has no directory to resolve "
                "relative paths against; use absolute paths or add "
                "'base_path'")
        paths = [f if os.path.isabs(f) else os.path.join(base, f)
                 for f in files]
        return CheckpointStateDict.from_files(paths), base

    if isinstance(checkpoint, str) and os.path.isfile(checkpoint):
        return CheckpointStateDict.from_files([checkpoint]), \
            os.path.dirname(os.path.abspath(checkpoint))

    raise TypeError(f"unsupported checkpoint spec: {checkpoint!r}")


def native_from_checkpoint(checkpoint, hf_config=None, dtype: Optional[str] = None):
    """checkpoint spec (+ optional HF config) → (native model, params).

    When ``hf_config`` is None the checkpoint directory must carry a
    ``config.json`` (HF layout) to resolve the architecture.
    """
    from ..inference.v2.model_implementations import resolve_container
    sd, base = load_checkpoint_state_dict(checkpoint)
    if hf_config is None:
        # never fall back to cwd: a raw-dict manifest has no anchor
        # directory, and passing None to from_pretrained would be treated
        # as the hub repo id "None" (network lookup + misleading error)
        if base is None or not os.path.exists(os.path.join(base, "config.json")):
            raise ValueError(
                "checkpoint has no config.json next to its weights; pass "
                "the HF config (or a model instance) to init_inference "
                "alongside `checkpoint`")
        from transformers import AutoConfig
        hf_config = AutoConfig.from_pretrained(base)
    container = resolve_container(hf_config)
    cfg = container.config(hf_config)
    if dtype:
        cfg = cfg.replace(dtype=dtype)
    params = container.build_params(sd, cfg)
    model = container.model_class(cfg)
    logger.info("Loaded %s from checkpoint files (%d tensors) without a "
                "torch module", type(model).__name__, len(sd))
    return model, params
