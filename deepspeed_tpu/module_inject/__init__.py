"""Module injection: user-model → native-model conversion (AutoTP analog)."""

from .replace_module import (hf_config_to_native, hf_to_native,  # noqa: F401
                             replace_transformer_layer)


def as_inference_model(model, config=None):
    """Normalize init_inference input → (CausalLM, params-or-None)."""
    from ..models.config import TransformerConfig
    from ..models.transformer import CausalLM, build_model

    if isinstance(model, CausalLM):
        return model, None
    if isinstance(model, (str, TransformerConfig)):
        return build_model(model), None
    # duck-type HF transformers torch modules
    if hasattr(model, "state_dict") and hasattr(model, "config"):
        return hf_to_native(model)
    raise TypeError(f"init_inference: unsupported model type {type(model)}")
