"""Module injection: user-model → native-model conversion (AutoTP analog)."""

from .load_checkpoint import (CheckpointStateDict,  # noqa: F401
                              load_checkpoint_state_dict,
                              native_from_checkpoint)
from .replace_module import (hf_config_to_native, hf_to_native,  # noqa: F401
                             replace_transformer_layer)


def as_inference_model(model, config=None):
    """Normalize init_inference input → (model, params-or-None).

    ``config.checkpoint`` (reference ``inference/engine.py:444``) loads
    weights from sharded checkpoint FILES: ``model`` may then be None (the
    checkpoint dir's config.json resolves the arch), an HF config, or an
    HF module whose weights are ignored in favor of the files.
    """
    from ..models.config import TransformerConfig
    from ..models.transformer import CausalLM, build_model

    ckpt = getattr(config, "checkpoint", None)
    if ckpt is not None:
        if isinstance(model, (CausalLM, TransformerConfig, str)):
            raise TypeError(
                "init_inference(checkpoint=...) maps HF-named tensors; pass "
                "model=None (checkpoint dir with config.json), an HF config, "
                "or an HF module — not a native model/preset")
        hf_config = getattr(model, "config", model)   # module → its config
        if hf_config is not None and not hasattr(hf_config, "architectures"):
            hf_config = None
        return native_from_checkpoint(ckpt, hf_config=hf_config)

    if isinstance(model, CausalLM):
        return model, None
    if isinstance(model, (str, TransformerConfig)):
        return build_model(model), None
    # duck-type HF transformers torch modules
    if hasattr(model, "state_dict") and hasattr(model, "config"):
        return hf_to_native(model)
    raise TypeError(f"init_inference: unsupported model type {type(model)}")
