"""HF-module injection: convert torch transformers models to the native
CausalLM + sharded params.

Analog of ``deepspeed/module_inject/replace_module.py:183``
(replace_transformer_layer) + ``auto_tp.py`` (AutoTP): the reference walks a
torch module replacing layers with fused-kernel modules and slicing weights
across TP ranks. Here conversion targets the native functional model whose
logical axes already encode TP ("heads"/"mlp"/"vocab" → tensor mesh axis), so
"AutoTP" is the sharding rule table — no per-arch slicing code.

Per-arch weight maps (reference ``module_inject/containers/*.py``):
gpt2, llama/llama2/llama3, mistral, mixtral, qwen2, opt.
"""

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..models.config import TransformerConfig
from ..models.transformer import CausalLM, build_model
from ..utils.logging import logger


def _np(t):
    """torch tensor → numpy (host)."""
    try:
        return t.detach().to("cpu").float().numpy()
    except Exception:
        return np.asarray(t, dtype=np.float32)


def hf_config_to_native(hf_cfg) -> TransformerConfig:
    """Map an HF PretrainedConfig to TransformerConfig."""
    arch = (getattr(hf_cfg, "architectures", None) or [type(hf_cfg).__name__])[0].lower()
    get = lambda *names, default=None: next(
        (getattr(hf_cfg, n) for n in names if getattr(hf_cfg, n, None) is not None), default)

    if "gpt2" in arch:
        return TransformerConfig(
            vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.n_embd,
            num_layers=hf_cfg.n_layer, num_heads=hf_cfg.n_head,
            intermediate_size=4 * hf_cfg.n_embd, max_seq_len=hf_cfg.n_positions,
            activation="gelu", norm="layernorm", position="learned",
            tie_embeddings=True, use_bias=True, norm_eps=hf_cfg.layer_norm_epsilon)
    # llama-family default (llama/mistral/mixtral/qwen2)
    num_experts = get("num_local_experts", "num_experts", default=0) or 0
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
        num_layers=get("num_hidden_layers", "n_layer"),
        num_heads=get("num_attention_heads", "n_head"),
        num_kv_heads=get("num_key_value_heads"),
        intermediate_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", default=4096),
        rope_theta=float(get("rope_theta", default=10000.0)),
        norm_eps=float(get("rms_norm_eps", "layer_norm_epsilon", default=1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", default=False)),
        num_experts=int(num_experts),
        num_experts_per_tok=int(get("num_experts_per_tok", default=2) or 2))


def _llama_like_params(sd: Dict[str, Any], cfg: TransformerConfig, prefix="model."):
    e, h, kvh, d = cfg.hidden_size, cfg.num_heads, cfg.kv_heads, cfg.dims_per_head
    L = cfg.num_layers

    def w(name):
        return _np(sd[name])

    layers = {"attn": {"wq": [], "wk": [], "wv": [], "wo": []},
              "norm1": {"scale": []}, "norm2": {"scale": []}}
    if cfg.is_moe:
        layers["mlp"] = {"router": [], "wi_gate": [], "wi_up": [], "wo": []}
    else:
        layers["mlp"] = {"wi_gate": [], "wi_up": [], "wo": []}

    for i in range(L):
        p = f"{prefix}layers.{i}."
        layers["attn"]["wq"].append(w(p + "self_attn.q_proj.weight").T.reshape(e, h, d))
        layers["attn"]["wk"].append(w(p + "self_attn.k_proj.weight").T.reshape(e, kvh, d))
        layers["attn"]["wv"].append(w(p + "self_attn.v_proj.weight").T.reshape(e, kvh, d))
        layers["attn"]["wo"].append(w(p + "self_attn.o_proj.weight").T.reshape(h, d, e))
        layers["norm1"]["scale"].append(w(p + "input_layernorm.weight"))
        layers["norm2"]["scale"].append(w(p + "post_attention_layernorm.weight"))
        if cfg.is_moe:
            x = cfg.num_experts
            layers["mlp"]["router"].append(w(p + "block_sparse_moe.gate.weight").T)
            layers["mlp"]["wi_gate"].append(np.stack(
                [w(p + f"block_sparse_moe.experts.{n}.w1.weight").T for n in range(x)]))
            layers["mlp"]["wi_up"].append(np.stack(
                [w(p + f"block_sparse_moe.experts.{n}.w3.weight").T for n in range(x)]))
            layers["mlp"]["wo"].append(np.stack(
                [w(p + f"block_sparse_moe.experts.{n}.w2.weight").T for n in range(x)]))
        else:
            layers["mlp"]["wi_gate"].append(w(p + "mlp.gate_proj.weight").T)
            layers["mlp"]["wi_up"].append(w(p + "mlp.up_proj.weight").T)
            layers["mlp"]["wo"].append(w(p + "mlp.down_proj.weight").T)

    stacked = {k: {kk: np.stack(vv) for kk, vv in sub.items()} for k, sub in layers.items()}
    emb = {"tok": w(prefix + "embed_tokens.weight")}
    if not cfg.tie_embeddings:
        emb["lm_head"] = w("lm_head.weight").T
    return {"embed": emb, "layers": stacked,
            "final_norm": {"scale": w(prefix + "norm.weight")}}


def _gpt2_params(sd: Dict[str, Any], cfg: TransformerConfig):
    e, h, d = cfg.hidden_size, cfg.num_heads, cfg.dims_per_head

    def w(name):
        return _np(sd[name])

    layers = {"attn": {"wq": [], "wk": [], "wv": [], "wo": [],
                       "bq": [], "bk": [], "bv": [], "bo": []},
              "mlp": {"wi": [], "wo": [], "bi": [], "bo": []},
              "norm1": {"scale": [], "bias": []}, "norm2": {"scale": [], "bias": []}}
    for i in range(cfg.num_layers):
        p = f"h.{i}." if f"h.{i}.ln_1.weight" in sd else f"transformer.h.{i}."
        ca = w(p + "attn.c_attn.weight")          # (E, 3E) Conv1D layout
        cb = w(p + "attn.c_attn.bias")            # (3E,)
        layers["attn"]["wq"].append(ca[:, :e].reshape(e, h, d))
        layers["attn"]["wk"].append(ca[:, e:2 * e].reshape(e, h, d))
        layers["attn"]["wv"].append(ca[:, 2 * e:].reshape(e, h, d))
        layers["attn"]["bq"].append(cb[:e].reshape(h, d))
        layers["attn"]["bk"].append(cb[e:2 * e].reshape(h, d))
        layers["attn"]["bv"].append(cb[2 * e:].reshape(h, d))
        layers["attn"]["wo"].append(w(p + "attn.c_proj.weight").reshape(h, d, e))
        layers["attn"]["bo"].append(w(p + "attn.c_proj.bias"))
        layers["mlp"]["wi"].append(w(p + "mlp.c_fc.weight"))
        layers["mlp"]["bi"].append(w(p + "mlp.c_fc.bias"))
        layers["mlp"]["wo"].append(w(p + "mlp.c_proj.weight"))
        layers["mlp"]["bo"].append(w(p + "mlp.c_proj.bias"))
        layers["norm1"]["scale"].append(w(p + "ln_1.weight"))
        layers["norm1"]["bias"].append(w(p + "ln_1.bias"))
        layers["norm2"]["scale"].append(w(p + "ln_2.weight"))
        layers["norm2"]["bias"].append(w(p + "ln_2.bias"))

    pre = "" if "wte.weight" in sd else "transformer."
    stacked = {k: {kk: np.stack(vv) for kk, vv in sub.items()} for k, sub in layers.items()}
    return {"embed": {"tok": w(pre + "wte.weight"), "pos": w(pre + "wpe.weight")},
            "layers": stacked,
            "final_norm": {"scale": w(pre + "ln_f.weight"), "bias": w(pre + "ln_f.bias")}}


def replace_transformer_layer(orig_layer_impl=None, model=None, checkpoint_dict=None,
                              config=None, model_config=None):
    """Reference-named entry (``replace_module.py:183``): converts a full HF
    model; returns (native_model, params)."""
    return hf_to_native(model)


def hf_to_native(hf_model) -> Tuple[CausalLM, Dict]:
    """Convert an HF transformers model instance → (CausalLM, param pytree)."""
    hf_cfg = hf_model.config
    cfg = hf_config_to_native(hf_cfg)
    sd = dict(hf_model.state_dict())
    arch = (getattr(hf_cfg, "architectures", None) or [type(hf_model).__name__])[0].lower()
    if "gpt2" in arch:
        params = _gpt2_params(sd, cfg)
    elif any(a in arch for a in ("llama", "mistral", "mixtral", "qwen")):
        prefix = "model." if any(k.startswith("model.") for k in sd) else ""
        params = _llama_like_params(sd, cfg, prefix=prefix)
    else:
        raise NotImplementedError(
            f"No injection policy for architecture {arch!r} "
            f"(reference parity list: containers/*.py); supported: gpt2, llama, "
            f"mistral, mixtral, qwen2")
    params = {k: _tree_to_jnp(v) for k, v in params.items()}
    n = sum(x.size for x in _leaves(params))
    logger.info(f"Injected {arch}: {n / 1e6:.1f}M params → native CausalLM")
    return CausalLM(cfg), params


def _tree_to_jnp(tree):
    if isinstance(tree, dict):
        return {k: _tree_to_jnp(v) for k, v in tree.items()}
    return jnp.asarray(tree)


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree
