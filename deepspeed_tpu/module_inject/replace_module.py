"""HF-module injection: convert torch transformers models to the native
CausalLM + sharded params.

Analog of ``deepspeed/module_inject/replace_module.py:183``
(replace_transformer_layer) + ``auto_tp.py`` (AutoTP): the reference walks a
torch module replacing layers with fused-kernel modules and slicing weights
across TP ranks. Here conversion targets the native functional model whose
logical axes already encode TP ("heads"/"mlp"/"vocab" → tensor mesh axis), so
"AutoTP" is the sharding rule table — no per-arch slicing code.

Per-arch weight maps (reference ``module_inject/containers/*.py``):
gpt2, llama/llama2/llama3, mistral, mixtral, qwen2, opt.
"""

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..models.config import TransformerConfig
from ..models.transformer import CausalLM, build_model
from ..utils.logging import logger


def _np(t):
    """torch tensor → numpy (host)."""
    try:
        return t.detach().to("cpu").float().numpy()
    except Exception:
        return np.asarray(t, dtype=np.float32)


def hf_config_to_native(hf_cfg) -> TransformerConfig:
    """Map an HF PretrainedConfig to TransformerConfig (container-resolved)."""
    from ..inference.v2.model_implementations import resolve_container
    return resolve_container(hf_cfg).config(hf_cfg)


def replace_transformer_layer(orig_layer_impl=None, model=None, checkpoint_dict=None,
                              config=None, model_config=None):
    """Reference-named entry (``replace_module.py:183``): converts a full HF
    model; returns (native_model, params)."""
    return hf_to_native(model)


def hf_to_native(hf_model) -> Tuple[CausalLM, Dict]:
    """Convert an HF transformers model instance → (CausalLM, param pytree).

    Delegates to the v2 model-implementation containers
    (``inference/v2/model_implementations/archs.py``) — the declarative
    per-arch weight mappings (llama/mistral/mixtral/qwen2/qwen2-moe/phi3/
    opt/gpt2) are the single source of truth for checkpoint injection.
    """
    from ..inference.v2.model_implementations import build_native
    model, params = build_native(hf_model)
    params = {k: _tree_to_jnp(v) for k, v in params.items()}
    n = sum(x.size for x in _leaves(params))
    logger.info(f"Injected {type(hf_model).__name__}: {n / 1e6:.1f}M params "
                f"→ native CausalLM")
    return model, params


def _tree_to_jnp(tree):
    if isinstance(tree, dict):
        return {k: _tree_to_jnp(v) for k, v in tree.items()}
    return jnp.asarray(tree)


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree
