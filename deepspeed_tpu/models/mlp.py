"""Residual MLP stack — second pipeline-capable native model family.

Counterpart of the reference's non-transformer test models
(``tests/unit/simple_model.py`` SimpleModel: a stack of linear layers used
to exercise engine/pipeline logic independently of attention). Implements
the same model protocol as ``CausalLM`` (``init`` / ``abstract_params`` /
``logical_axes`` / ``loss``) plus the pipeline three-segment protocol
(``pipe_embed`` / ``pipe_layer`` / ``pipe_loss``) consumed by
``runtime/pipe/engine.py build_pipeline_1f1b``, proving the compiled 1F1B
engine is model-generic (the reference PipelineModule accepts any
LayerSpec sequence, ``runtime/pipe/module.py:86``).
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass
class MLPConfig:
    in_features: int = 32
    hidden_size: int = 64
    num_layers: int = 2
    num_classes: int = 8
    act_dtype: type = jnp.float32


class ResidualMLP:
    """in → Linear → [num_layers × residual (Linear, gelu, Linear)] → head.

    params = {"embed": {"win": ..., "bin": ...},
              "layers": stacked {"w1","b1","w2","b2"},
              "head": {"wout": ..., "bout": ...}}
    """

    def __init__(self, cfg: MLPConfig):
        self.cfg = cfg

    def init(self, rng):
        cfg = self.cfg
        r_in, r_layers, r_out = jax.random.split(rng, 3)
        scale_in = 1.0 / jnp.sqrt(cfg.in_features)
        embed = {"win": jax.random.normal(r_in, (cfg.in_features, cfg.hidden_size)) * scale_in,
                 "bin": jnp.zeros((cfg.hidden_size,))}
        scale_h = 1.0 / jnp.sqrt(cfg.hidden_size)

        def one_layer(r):
            r1, r2 = jax.random.split(r)
            return {"w1": jax.random.normal(r1, (cfg.hidden_size, cfg.hidden_size)) * scale_h,
                    "b1": jnp.zeros((cfg.hidden_size,)),
                    "w2": jax.random.normal(r2, (cfg.hidden_size, cfg.hidden_size)) * scale_h,
                    "b2": jnp.zeros((cfg.hidden_size,))}

        per_layer = [one_layer(r) for r in jax.random.split(r_layers, cfg.num_layers)]
        layers = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
        head = {"wout": jax.random.normal(r_out, (cfg.hidden_size, cfg.num_classes)) * scale_h,
                "bout": jnp.zeros((cfg.num_classes,))}
        return {"embed": embed, "layers": layers, "head": head}

    def abstract_params(self):
        return jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))

    def logical_axes(self):
        return {
            "embed": {"win": (None, "mlp"), "bin": ("mlp",)},
            "layers": {"w1": ("layers", None, "mlp"), "b1": ("layers", "mlp"),
                       "w2": ("layers", "mlp", None), "b2": ("layers", "mlp")},
            "head": {"wout": (None, None), "bout": (None,)},
        }

    # -- pipeline three-segment protocol --

    def pipe_embed(self, other, batch_mb):
        x = batch_mb["x"].astype(self.cfg.act_dtype)
        return x @ other["embed"]["win"].astype(x.dtype) + other["embed"]["bin"].astype(x.dtype)

    def pipe_layer(self, lp, h):
        y = jax.nn.gelu(h @ lp["w1"].astype(h.dtype) + lp["b1"].astype(h.dtype))
        y = y @ lp["w2"].astype(h.dtype) + lp["b2"].astype(h.dtype)
        return h + y

    def pipe_loss(self, other, h, batch_mb):
        logits = (h @ other["head"]["wout"].astype(h.dtype)
                  + other["head"]["bout"].astype(h.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        labels = batch_mb["y"]
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))

    # -- plain (non-pipelined) loss for parity tests --

    def loss(self, params, batch):
        other = {k: v for k, v in params.items() if k != "layers"}
        h = self.pipe_embed(other, batch)

        def one(hh, lp):
            return self.pipe_layer(lp, hh), None

        h, _ = jax.lax.scan(one, h, params["layers"])
        return self.pipe_loss(other, h, batch)
