"""BERT-family bidirectional encoder with a masked-LM head.

The reference trains BERT-large under ZeRO-1/2 (BASELINE acceptance
config 2) and serves BERT through kernel injection
(``module_inject/containers/bert.py``, ``model_implementations/transformers/
ds_bert.py``). Here the encoder reuses the decoder's layer primitives with
three twists carried by ``TransformerConfig``: ``post_norm`` (layernorm
AFTER each residual add), ``causal=False`` (bidirectional attention;
padding handled by the segment-ids mask), and ``mlm_head`` (dense + gelu +
layernorm + tied decoder with a vocab bias).
"""

import jax
import jax.numpy as jnp

from . import layers as L
from .config import TransformerConfig
from .transformer import (CausalLM, _axes_of, lm_head_logits,
                          logit_buffer_bytes, masked_token_nll)


def init_mlm_head(rng, cfg: TransformerConfig):
    """BERT ``cls.predictions``: transform dense + LN, tied decoder + bias."""
    e = cfg.hidden_size
    params = {
        "dense": L._normal(rng, (e, e), cfg.p_dtype, 0.02),
        "bias": L._zeros((e,), cfg.p_dtype),
        "norm": L.init_norm(cfg)[0],
        "decoder_bias": L._zeros((cfg.vocab_size,), cfg.p_dtype),
    }
    axes = {
        "dense": ("embed", "unmodeled"),
        "bias": ("embed",),
        "norm": L.init_norm(cfg)[1],
        "decoder_bias": ("vocab",),
    }
    return params, axes


class EncoderLM(CausalLM):
    """Bidirectional encoder (BERT/DistilBERT) trained with masked-LM loss.

    ``batch``: input_ids, labels (-100 = unmasked/ignored), optional
    attention_mask (1 = real token) and token_type_ids.
    """

    def init(self, rng):
        params = super().init(rng)
        if self.cfg.mlm_head:
            r_mlm = jax.random.fold_in(rng, 0x3A)
            params["mlm"] = init_mlm_head(r_mlm, self.cfg)[0]
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        if self.cfg.mlm_head:
            axes["mlm"] = _axes_of(lambda r: init_mlm_head(r, self.cfg))
        return axes

    def _transform(self, params, h):
        """MLM transform (dense + gelu + LN), presence-gated: checkpoints
        loaded without a cls head (e.g. classification fine-tunes) skip it."""
        cfg = self.cfg
        if not (cfg.mlm_head and "mlm" in params):
            return h
        dt = cfg.act_dtype
        m = params["mlm"]
        h = jnp.einsum("bse,eo->bso", h, m["dense"].astype(dt)) + m["bias"].astype(dt)
        h = jax.nn.gelu(h, approximate=cfg.activation != "gelu_exact")
        return L.apply_norm(m["norm"], h, cfg)

    def apply(self, params, input_ids, *, positions=None, segment_ids=None,
              token_type_ids=None, attention_mask=None, return_aux_loss=False):
        """input_ids (B, S) → MLM logits (B, S, V)."""
        cfg = self.cfg
        dt = cfg.act_dtype
        if segment_ids is None and attention_mask is not None:
            # 0/1 padding mask as segment ids: real tokens attend only real
            # tokens, pads only pads (whose outputs the loss ignores)
            segment_ids = attention_mask.astype(jnp.int32)
        h, aux = self.hidden_states(params, input_ids, positions=positions,
                                    segment_ids=segment_ids,
                                    token_type_ids=token_type_ids)
        h = self._transform(params, h)
        w, transpose = self._lm_head_weight(params)
        bias = (params["mlm"]["decoder_bias"]
                if cfg.mlm_head and "mlm" in params else None)
        logits = lm_head_logits(h, w, transpose, dt, bias)
        if return_aux_loss:
            return logits, aux
        return logits

    def head_loss(self, head_params, h, labels, loss_mask=None):
        """MLM transform + cross-entropy from hidden states; labels use the
        -100 ignore convention. Routes through the vocab-chunked fused CE
        (decoder bias folded in as an extra input column) when the (B, S, V)
        logits would be large — the same memory bound CausalLM.head_loss
        enforces (bert-large vocab 30k at batch 32 is ~2 GB of fp32 logits).
        """
        cfg = self.cfg
        h = self._transform(head_params, h)
        mask = (labels != -100).astype(jnp.float32)
        if loss_mask is not None:
            mask = mask * loss_mask
        safe_labels = jnp.maximum(labels, 0)
        w, transpose = self._lm_head_weight(head_params)
        wv = w.T if transpose else w                      # (V, E)
        bias = None
        if cfg.mlm_head and "mlm" in head_params:
            bias = head_params["mlm"]["decoder_bias"]
        if (cfg.loss_chunks > 0 and cfg.vocab_size >= 4096
                and logit_buffer_bytes(labels.size, cfg)
                > cfg.loss_chunk_threshold_bytes):
            from ..ops.cross_entropy import lm_cross_entropy
            if bias is not None:
                # fold the vocab bias into the matmul: logits = [h, 1] @ [W, b]^T
                ones = jnp.ones(h.shape[:-1] + (1,), h.dtype)
                h = jnp.concatenate([h, ones], axis=-1)
                wv = jnp.concatenate([wv, bias[:, None].astype(wv.dtype)], axis=-1)
            return lm_cross_entropy(h, wv.astype(h.dtype), safe_labels,
                                    loss_mask=mask, n_chunks=cfg.loss_chunks)
        logits = lm_head_logits(h, wv, False, cfg.act_dtype, bias)
        return masked_token_nll(logits, safe_labels, mask)

    def loss(self, params, batch):
        """Masked-LM cross-entropy over positions where labels != -100."""
        segment_ids = batch.get("segment_ids")
        if segment_ids is None and batch.get("attention_mask") is not None:
            segment_ids = batch["attention_mask"].astype(jnp.int32)
        h, _ = self.hidden_states(params, batch["input_ids"],
                                  positions=batch.get("positions"),
                                  segment_ids=segment_ids,
                                  token_type_ids=batch.get("token_type_ids"))
        return self.head_loss(params, h, batch["labels"],
                              loss_mask=batch.get("loss_mask"))
