"""Native model zoo + adapters for user-supplied models."""

from typing import Any, Callable, Optional

import jax

from .config import PRESETS, TransformerConfig, get_config  # noqa: F401
from .transformer import CausalLM, build_model  # noqa: F401
from .bert import EncoderLM  # noqa: F401


class FunctionalModel:
    """Adapter for a bare ``(params, loss_fn)`` pair.

    ``loss_fn(params, batch) -> scalar`` drives training; ``apply_fn`` is
    optional when only training is needed.
    """

    def __init__(self, params, loss_fn: Callable, apply_fn: Optional[Callable] = None,
                 logical_axes=None):
        self._params = params
        self._loss_fn = loss_fn
        self._apply_fn = apply_fn
        self._logical = logical_axes

    def init(self, rng):
        return self._params

    def abstract_params(self):
        return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self._params)

    def logical_axes(self):
        if self._logical is not None:
            return self._logical

        def default_axes(x):
            if x.ndim == 0:
                return ()
            return ("embed",) + ("unmodeled",) * (x.ndim - 1)
        return jax.tree.map(default_axes, self._params)

    def apply(self, params, *args, **kwargs):
        assert self._apply_fn is not None, "FunctionalModel built without apply_fn"
        return self._apply_fn(params, *args, **kwargs)

    def loss(self, params, batch):
        return self._loss_fn(params, batch)


class FlaxModel:
    """Adapter for a flax ``nn.Module`` with an LM-style loss."""

    def __init__(self, module, example_batch, loss_fn=None):
        self.module = module
        self._example = example_batch
        self._loss_fn = loss_fn

    def init(self, rng):
        return self.module.init(rng, self._example["input_ids"])["params"]

    def abstract_params(self):
        return jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))

    def logical_axes(self):
        def default_axes(x):
            if x.ndim == 0:
                return ()
            return ("embed",) + ("unmodeled",) * (x.ndim - 1)
        return jax.tree.map(default_axes, self.abstract_params())

    def apply(self, params, *args, **kwargs):
        return self.module.apply({"params": params}, *args, **kwargs)

    def loss(self, params, batch):
        if self._loss_fn is not None:
            return self._loss_fn(self.module, params, batch)
        import jax.numpy as jnp
        logits = self.apply(params, batch["input_ids"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, batch["labels"][..., None], axis=-1)[..., 0]
        return jnp.mean(nll)


def as_model(model: Any):
    """Normalize user input (CausalLM, adapter, preset name, config)."""
    if isinstance(model, str):
        return build_model(model)
    if isinstance(model, TransformerConfig):
        return build_model(model)
    if hasattr(model, "init") and hasattr(model, "loss"):
        return model
    raise TypeError(f"Unsupported model type {type(model)}; expected CausalLM, FunctionalModel, "
                    "FlaxModel, preset name, or TransformerConfig "
                    "(wrap flax modules with deepspeed_tpu.models.FlaxModel)")
