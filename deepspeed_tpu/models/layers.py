"""Transformer layer primitives: pure functions over explicit param pytrees.

Every parameter leaf has a parallel *logical-axes* annotation (see
``parallel/sharding.py``) so ZeRO/TP/EP sharding is declarative. Initializers
follow the conventions the reference's target models use (normal(0.02) for
embeddings, scaled-variance for projections).
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.attention import multihead_attention, decode_attention
from .config import TransformerConfig

# ---- init helpers -------------------------------------------------------

def _normal(rng, shape, dtype, stddev):
    return (jax.random.normal(rng, shape, jnp.float32) * stddev).astype(dtype)


def _zeros(shape, dtype):
    return jnp.zeros(shape, dtype)


def _ones(shape, dtype):
    return jnp.ones(shape, dtype)


def bcast(w, ndim: int):
    """Left-pad ``w`` with size-1 axes to rank ``ndim`` — the explicit form
    of trailing-dim weight broadcasting ((B, S, E) op (E,) etc.), so the
    serving forward stays legal under ``jax_numpy_rank_promotion="raise"``
    (the GRAFT_SANITIZE suite mode and graft-lint's dtype/rank hygiene)."""
    return w.reshape((1,) * (ndim - w.ndim) + w.shape)


def dq(w, dt):
    """Dequantize-or-cast a weight leaf to compute dtype ``dt``.

    Serving-side weight quantization (``inference/v2/model_implementations/
    quantize.py``) replaces a matmul weight leaf with ``{"q": int8,
    "s": f32 keepdims-scale}``; everything else stays a plain array. The
    structure check is a static (trace-time) decision, so unquantized
    models trace the exact pre-quantization program, and the dequantized
    product broadcasts the per-output-channel scale back over the reduced
    axes (keepdims size-1 dims)."""
    if isinstance(w, dict) and "q" in w:
        return w["q"].astype(dt) * w["s"].astype(dt)
    return w.astype(dt)


# ---- norms --------------------------------------------------------------

def init_norm(cfg: TransformerConfig):
    params = {"scale": _ones((cfg.hidden_size,), cfg.p_dtype)}
    axes = {"scale": ("embed",)}
    if cfg.norm == "layernorm":
        params["bias"] = _zeros((cfg.hidden_size,), cfg.p_dtype)
        axes["bias"] = ("embed",)
    return params, axes


def apply_norm(params, x, cfg: TransformerConfig):
    x32 = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + cfg.norm_eps)
        return (y * bcast(params["scale"].astype(jnp.float32),
                          y.ndim)).astype(x.dtype)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
    y = (y * bcast(params["scale"].astype(jnp.float32), y.ndim)
         + bcast(params["bias"].astype(jnp.float32), y.ndim))
    return y.astype(x.dtype)


# ---- rotary embeddings --------------------------------------------------

def rope_frequencies(cfg: TransformerConfig):
    d = int(cfg.dims_per_head * cfg.rotary_pct)  # partial rotary (GPT-NeoX)
    d -= d % 2
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    return inv_freq  # (d/2,)


def apply_rope(x, positions, inv_freq, *, interleaved=False):
    """x: (B, S, H, D); positions: (B, S) int32.

    ``inv_freq`` has rd/2 entries where rd <= D is the rotary span (partial
    rotary, GPT-NeoX ``rotary_pct``); dims past rd pass through untouched.
    ``interleaved`` uses the (x0,x1),(x2,x3)... pair layout (GPT-J/NeoX
    checkpoints) instead of split halves (Llama).
    """
    rd = 2 * inv_freq.shape[0]
    rot = x[..., :rd].astype(jnp.float32)
    angles = (positions[..., None].astype(jnp.float32)
              * inv_freq[None, None, :])                     # (B, S, rd/2)
    sin = jnp.sin(angles)[:, :, None, :]
    cos = jnp.cos(angles)[:, :, None, :]
    if interleaved:
        x1 = rot[..., 0::2]
        x2 = rot[..., 1::2]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.stack([o1, o2], axis=-1).reshape(rot.shape)
    else:
        x1, x2 = jnp.split(rot, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if rd < x.shape[-1]:
        out = jnp.concatenate([out, x[..., rd:].astype(jnp.float32)], axis=-1)
    return out.astype(x.dtype)


# ---- ALiBi --------------------------------------------------------------

def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (Press et al.; the layout HF BLOOM uses).

    For a power-of-two head count: geometric sequence starting at
    2^(-8/n). Otherwise the closest power of two's sequence is extended
    with the odd-indexed slopes of the doubled sequence.
    """
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        s = pow2_slopes(num_heads)
    else:
        base = 2 ** math.floor(math.log2(num_heads))
        s = pow2_slopes(base)
        extra = pow2_slopes(2 * base)[0::2][: num_heads - base]
        s = s + extra
    return jnp.asarray(s, jnp.float32)


def alibi_bias(num_heads: int, q_pos, k_pos) -> jnp.ndarray:
    """Additive attention bias slope_h * (k - q): (..., H, Sq, Sk).

    q_pos: (Sq,) or (B, Sq); k_pos: (Sk,). The relative form differs from
    HF's per-key-position form by a per-row constant, which softmax
    cancels.
    """
    slopes = alibi_slopes(num_heads)                                   # (H,)
    rel = (k_pos[None, :] - q_pos[..., :, None]).astype(jnp.float32)   # (..., Sq, Sk)
    return slopes[:, None, None] * rel[..., None, :, :]


# ---- attention ----------------------------------------------------------

def init_attention(rng, cfg: TransformerConfig):
    e, h, kvh, d = cfg.hidden_size, cfg.num_heads, cfg.kv_heads, cfg.dims_per_head
    r = jax.random.split(rng, 4)
    std = 0.02
    params = {
        "wq": _normal(r[0], (e, h, d), cfg.p_dtype, std),
        "wk": _normal(r[1], (e, kvh, d), cfg.p_dtype, std),
        "wv": _normal(r[2], (e, kvh, d), cfg.p_dtype, std),
        "wo": _normal(r[3], (h, d, e), cfg.p_dtype, std / math.sqrt(2 * cfg.num_layers)),
    }
    axes = {
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
    }
    if cfg.use_bias or cfg.qkv_bias:
        params.update(bq=_zeros((h, d), cfg.p_dtype), bk=_zeros((kvh, d), cfg.p_dtype),
                      bv=_zeros((kvh, d), cfg.p_dtype))
        axes.update(bq=("heads", "head_dim"), bk=("kv_heads", "head_dim"),
                    bv=("kv_heads", "head_dim"))
    out_bias = cfg.use_bias if cfg.out_bias is None else cfg.out_bias
    if out_bias:
        params.update(bo=_zeros((e,), cfg.p_dtype))
        axes.update(bo=("embed",))
    if cfg.qk_norm:
        q_shape, k_shape = {
            "full": ((h * d,), (kvh * d,)),
            "head_dim": ((d,), (d,)),
            "per_head": ((h, d), (kvh, d)),
        }[cfg.qk_norm]
        for nm, shape in (("q_norm", q_shape), ("k_norm", k_shape)):
            grp = {"scale": _ones(shape, cfg.p_dtype)}
            grp_axes = {"scale": tuple("unmodeled" for _ in shape)}
            if cfg.norm == "layernorm" and cfg.qk_norm_bias:
                grp["bias"] = _zeros(shape, cfg.p_dtype)
                grp_axes["bias"] = grp_axes["scale"]
            params[nm] = grp
            axes[nm] = grp_axes
    return params, axes


def apply_qk_norm(norm_params, x, cfg: TransformerConfig):
    """Normalize q or k heads: x (B, S, H, D).

    "full" normalizes the flattened per-token (H*D) vector (MPT qk_ln:
    LayerNorm(d_model) before the head split); "head_dim"/"per_head"
    normalize each head's D dims (Phi shares one (D,) weight, StableLM
    stacks (H, D)) — the stats are per-head either way, only the weight
    sharing differs, and both weight shapes broadcast over (B, S, H, D).
    """
    b, s, h, d = x.shape
    x32 = x.astype(jnp.float32)
    if cfg.qk_norm == "full":
        x32 = x32.reshape(b, s, h * d)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + cfg.norm_eps)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
    y = y * bcast(norm_params["scale"].astype(jnp.float32), y.ndim)
    if "bias" in norm_params:
        y = y + bcast(norm_params["bias"].astype(jnp.float32), y.ndim)
    return y.reshape(b, s, h, d).astype(x.dtype)


def apply_attention(params, x, cfg: TransformerConfig, *, positions=None, inv_freq=None,
                    segment_ids=None, kv_cache=None, cache_len=None, attn_bias=None,
                    window=None):
    """x: (B, S, E). Returns (out, new_kv_cache).

    Training: kv_cache None. Decode: kv_cache = (k, v) with shape
    (B, S_max, KVH, D); new tokens are written at ``cache_len`` offsets.
    ``attn_bias``: precomputed additive bias (ALiBi) — layer-invariant, so
    callers scanning over layers build it ONCE and pass it down (computed
    here only as a standalone-call fallback).
    ``window``: sliding-window width for this layer (static int, or traced
    scalar under a scan over mixed local/global layers; <= 0 = global).
    """
    if window is None and cfg.sliding_window is not None and cfg.local_attention_every is None:
        window = cfg.sliding_window   # uniform window (Mistral)
    dt = cfg.act_dtype
    q = jnp.einsum("bse,ehd->bshd", x, params["wq"].astype(dt))
    k = jnp.einsum("bse,ehd->bshd", x, params["wk"].astype(dt))
    v = jnp.einsum("bse,ehd->bshd", x, params["wv"].astype(dt))
    if cfg.use_bias or cfg.qkv_bias:
        q = q + bcast(params["bq"].astype(dt), q.ndim)
        k = k + bcast(params["bk"].astype(dt), k.ndim)
        v = v + bcast(params["bv"].astype(dt), v.ndim)
    if cfg.qk_norm:
        q = apply_qk_norm(params["q_norm"], q, cfg)
        k = apply_qk_norm(params["k_norm"], k, cfg)
    if cfg.position == "rope":
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])
        q = apply_rope(q, positions, inv_freq, interleaved=cfg.rope_interleaved)
        k = apply_rope(k, positions, inv_freq, interleaved=cfg.rope_interleaved)

    new_cache = None
    if kv_cache is not None:
        ck, cv = kv_cache
        # write the S new entries at cache_len offset (decode S is typically 1)
        b, s = x.shape[:2]
        idx = cache_len[:, None] + jnp.arange(s)[None, :]  # (B, S)
        ck = _scatter_cache(ck, k, idx)
        cv = _scatter_cache(cv, v, idx)
        new_cache = (ck, cv)
        bias = attn_bias
        if cfg.position == "alibi" and bias is None:
            k_pos = jnp.arange(ck.shape[1])
            bias = alibi_bias(cfg.num_heads, idx, k_pos)   # (B, H, S, S_max)
        out = decode_attention(q, ck, cv, cache_len + s, bias=bias, window=window,
                               scale=cfg.attn_scale, softcap=cfg.attn_softcap)
    else:
        impl = None if cfg.attn_impl == "auto" else cfg.attn_impl
        slopes = None
        if cfg.position == "alibi" and attn_bias is None:
            # slopes, not a bias tensor: the flash kernel computes the
            # ALiBi term in-kernel; XLA fallbacks expand it themselves
            slopes = alibi_slopes(cfg.num_heads)
        out = multihead_attention(q, k, v, causal=cfg.causal, segment_ids=segment_ids,
                                  bias=attn_bias, alibi_slopes=slopes,
                                  window=window, impl=impl, scale=cfg.attn_scale,
                                  softcap=cfg.attn_softcap)

    y = jnp.einsum("bshd,hde->bse", out, params["wo"].astype(dt))
    if "bo" in params:
        y = y + bcast(params["bo"].astype(dt), y.ndim)
    return y, new_cache


def _scatter_cache(cache, new, idx):
    """cache: (B, S_max, H, D); new: (B, S, H, D); idx: (B, S) positions."""
    b = cache.shape[0]
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], idx.shape)
    return cache.at[bidx, idx].set(new.astype(cache.dtype))


# ---- MLP ----------------------------------------------------------------

def init_mlp(rng, cfg: TransformerConfig):
    e, f = cfg.hidden_size, cfg.ffn_size
    r = jax.random.split(rng, 3)
    std = 0.02
    if cfg.activation in ("swiglu", "geglu"):
        params = {
            "wi_gate": _normal(r[0], (e, f), cfg.p_dtype, std),
            "wi_up": _normal(r[1], (e, f), cfg.p_dtype, std),
            "wo": _normal(r[2], (f, e), cfg.p_dtype, std / math.sqrt(2 * cfg.num_layers)),
        }
        axes = {"wi_gate": ("embed", "mlp"), "wi_up": ("embed", "mlp"), "wo": ("mlp", "embed")}
    else:
        params = {
            "wi": _normal(r[0], (e, f), cfg.p_dtype, std),
            "wo": _normal(r[2], (f, e), cfg.p_dtype, std / math.sqrt(2 * cfg.num_layers)),
        }
        axes = {"wi": ("embed", "mlp"), "wo": ("mlp", "embed")}
    mlp_bias = cfg.use_bias if cfg.mlp_bias is None else cfg.mlp_bias
    if mlp_bias:
        params.update(bi=_zeros((f,), cfg.p_dtype), bo=_zeros((e,), cfg.p_dtype))
        axes.update(bi=("mlp",), bo=("embed",))
    return params, axes


def apply_mlp(params, x, cfg: TransformerConfig, reduce=None):
    """``reduce`` (tensor-parallel serving): applied to the w_out product
    BEFORE the output bias — with the intermediate dim sharded, the product
    is a partial sum the caller all-reduces, and the replicated bias must
    be added exactly once (after the reduce), not once per shard."""
    dt = cfg.act_dtype
    mlp_bias = cfg.use_bias if cfg.mlp_bias is None else cfg.mlp_bias
    if cfg.activation in ("swiglu", "geglu"):
        g = jnp.einsum("bse,ef->bsf", x, dq(params["wi_gate"], dt))
        u = jnp.einsum("bse,ef->bsf", x, dq(params["wi_up"], dt))
        gate = (jax.nn.gelu(g, approximate=True) if cfg.activation == "geglu"
                else jax.nn.silu(g))
        h = gate * u
    else:
        h = jnp.einsum("bse,ef->bsf", x, dq(params["wi"], dt))
        if mlp_bias:
            h = h + bcast(params["bi"].astype(dt), h.ndim)
        if cfg.activation == "relu":
            h = jax.nn.relu(h)
        else:  # "gelu" = tanh approximation (gelu_new); "gelu_exact" = erf
            h = jax.nn.gelu(h, approximate=cfg.activation != "gelu_exact")
    y = jnp.einsum("bsf,fe->bse", h, dq(params["wo"], dt))
    if reduce is not None:
        y = reduce(y)
    if mlp_bias:
        y = y + bcast(params["bo"].astype(dt), y.ndim)
    return y


# ---- MoE MLP ------------------------------------------------------------

def init_moe_mlp(rng, cfg: TransformerConfig):
    """Mixtral-style top-k routed experts with swiglu experts (+ optional
    Qwen2-MoE always-on shared expert with its own sigmoid gate)."""
    e, f, x = cfg.hidden_size, cfg.moe_ffn_size, cfg.num_experts
    r = jax.random.split(rng, 8)
    std = 0.02
    params = {
        "router": _normal(r[0], (e, x), cfg.p_dtype, std),
        "wi_gate": _normal(r[1], (x, e, f), cfg.p_dtype, std),
        "wi_up": _normal(r[2], (x, e, f), cfg.p_dtype, std),
        "wo": _normal(r[3], (x, f, e), cfg.p_dtype, std / math.sqrt(2 * cfg.num_layers)),
    }
    axes = {
        "router": ("embed", "unmodeled"),
        "wi_gate": ("expert", "embed", "mlp"),
        "wi_up": ("expert", "embed", "mlp"),
        "wo": ("expert", "mlp", "embed"),
    }
    if cfg.moe_shared_expert_size:
        s = cfg.moe_shared_expert_size
        params.update(
            shared_wi_gate=_normal(r[4], (e, s), cfg.p_dtype, std),
            shared_wi_up=_normal(r[5], (e, s), cfg.p_dtype, std),
            shared_wo=_normal(r[6], (s, e), cfg.p_dtype,
                              std / math.sqrt(2 * cfg.num_layers)),
            shared_gate=_normal(r[7], (e, 1), cfg.p_dtype, std))
        axes.update(shared_wi_gate=("embed", "mlp"), shared_wi_up=("embed", "mlp"),
                    shared_wo=("mlp", "embed"), shared_gate=("embed", "unmodeled"))
    return params, axes


def _apply_shared_expert(params, x, cfg: TransformerConfig):
    """Qwen2-MoE shared expert: swiglu MLP weighted by a sigmoid gate."""
    dt = cfg.act_dtype
    g = jnp.einsum("...e,ef->...f", x, params["shared_wi_gate"].astype(dt))
    u = jnp.einsum("...e,ef->...f", x, params["shared_wi_up"].astype(dt))
    sh = jnp.einsum("...f,fe->...e", jax.nn.silu(g) * u,
                    params["shared_wo"].astype(dt))
    gate = jax.nn.sigmoid(
        jnp.einsum("...e,eo->...o", x, params["shared_gate"].astype(dt)))
    return gate * sh


def apply_moe_grouped(params, x, cfg: TransformerConfig):
    """Dropless grouped-GEMM MoE (megablox pattern; reference analog:
    ``inference/v2/kernels/cutlass_ops/moe_gemm``): tokens are sorted by
    assigned expert and each expert's contiguous row group hits one MXU-tiled
    ``ragged_dot`` — no capacity buffers, no dense (T, X, C) dispatch
    einsums, no token dropping. Selected by ``moe_impl: "grouped"``;
    requires an unsharded expert axis (EP uses the einsum/all-to-all path).
    """
    from ..moe.sharded_moe import topk_gating_grouped
    from ..ops.pallas.grouped_gemm import moe_expert_ffn
    dt = cfg.act_dtype
    b, s, e = x.shape
    k = cfg.num_experts_per_tok
    n_exp = cfg.num_experts
    tokens = x.reshape(b * s, e)
    t = tokens.shape[0]

    logits = jnp.einsum("te,ex->tx", tokens.astype(jnp.float32),
                        params["router"].astype(jnp.float32))
    topk_idx, w, aux_loss = topk_gating_grouped(logits, k=k,
                                                normalize=cfg.moe_norm_topk)

    expert_of_row = topk_idx.reshape(-1)                      # (T*k,)
    order = jnp.argsort(expert_of_row, stable=True)
    tok_of_sorted = order // k                                # token each row copies
    sorted_tokens = jnp.take(tokens, tok_of_sorted, axis=0)   # (T*k, E)
    group_sizes = jnp.bincount(expert_of_row, length=n_exp).astype(jnp.int32)

    rows = moe_expert_ffn(sorted_tokens.astype(dt),
                          params["wi_gate"].astype(dt),
                          params["wi_up"].astype(dt),
                          params["wo"].astype(dt), group_sizes)
    w_sorted = jnp.take(w.reshape(-1), order, axis=0).astype(dt)
    out = jnp.zeros((t, e), dt).at[tok_of_sorted].add(rows * w_sorted[:, None])
    if cfg.moe_shared_expert_size:
        out = out + _apply_shared_expert(params, tokens.astype(dt), cfg)
    return out.reshape(b, s, e), aux_loss


def apply_moe_grouped_ep(params, x, cfg: TransformerConfig, mesh):
    """Dropless grouped MoE under a SHARDED expert axis (megablox-under-EP;
    reference analog: ``inference/v2/kernels/cutlass_ops/moe_gemm`` +
    ``deepspeed/moe/sharded_moe.py:533 _AllToAll``).

    A shard_map manual over the token-carrying axes + ``expert``:
    each device routes its local tokens, lays rows destined to expert-shard
    ``s`` into slot block ``s`` of a static (ep, R, E) buffer, all-to-all
    over the expert axis, runs ONE local ``ragged_dot`` over the received
    rows sorted by local expert (ragged_dot zero-fills and skips the empty
    tail, so compute scales with the rows actually routed here), and
    all-to-alls results back for the weighted combine. R = T_local * k — the
    worst case, so NO token is ever dropped regardless of routing imbalance
    (the capacity-einsum path drops at C); memory is over-provisioned
    instead, the standard static-shape tradeoff on XLA.
    """
    from jax.sharding import PartitionSpec as P
    from ..moe.sharded_moe import topk_gating_grouped
    from ..ops.pallas.grouped_gemm import moe_expert_ffn

    from ..utils import groups as _groups

    dt = cfg.act_dtype
    k = cfg.num_experts_per_tok
    n_exp = cfg.num_experts
    ep = mesh.shape["expert"]
    n_local = n_exp // ep
    # tokens' batch dim is sharded over ALL data-like axes (expert included:
    # EP groups split the batch, reference groups.py expert_parallel groups)
    batch_axes = tuple(a for a in _groups.BATCH_AXES
                       if mesh.shape.get(a, 1) > 1)
    seq_axis = "seq" if mesh.shape.get("seq", 1) > 1 else None
    manual = set(batch_axes) | {"expert"} | ({seq_axis} if seq_axis else set())

    def body(router, wi_gate, wi_up, wo, x):
        b, s, e = x.shape
        tokens = x.reshape(b * s, e)
        t_loc = tokens.shape[0]
        r_buf = t_loc * k

        logits = jnp.einsum("te,ex->tx", tokens.astype(jnp.float32),
                            router.astype(jnp.float32))
        topk_idx, w, _ = topk_gating_grouped(logits, k=k,
                                             normalize=cfg.moe_norm_topk)
        # GShard aux over the GLOBAL token set, from psum'd sufficient
        # statistics (per-shard means of products != products of global
        # means; the einsum path aggregates globally, so must this one)
        gates = jax.nn.softmax(logits, axis=-1)
        mask_tx = jnp.sum(jax.nn.one_hot(topk_idx, n_exp, dtype=jnp.float32),
                          axis=1)
        stats = jax.lax.pmean(
            jnp.stack([jnp.mean(gates, axis=0), jnp.mean(mask_tx, axis=0)]),
            tuple(sorted(manual)))
        aux = n_exp * jnp.sum(stats[0] * stats[1])
        er = topk_idx.reshape(-1)                       # (T*k,) global expert
        ts = er // n_local                              # target expert shard
        le = er % n_local                               # local id on target

        order = jnp.argsort(ts, stable=True)
        ts_s = jnp.take(ts, order)
        counts = jnp.bincount(ts_s, length=ep)
        starts = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                                  jnp.cumsum(counts)[:-1]])
        pos = jnp.arange(r_buf) - jnp.take(starts, ts_s)   # rank within shard
        slot = ts_s * r_buf + pos                          # (T*k,) send slot
        tok_of_sorted = order // k
        send = jnp.zeros((ep * r_buf, e), dt).at[slot].set(
            jnp.take(tokens, tok_of_sorted, axis=0).astype(dt))
        send_le = jnp.full((ep * r_buf,), n_local, jnp.int32).at[slot].set(
            jnp.take(le, order))

        recv = jax.lax.all_to_all(send.reshape(ep, r_buf, e), "expert", 0, 0,
                                  tiled=False).reshape(ep * r_buf, e)
        recv_le = jax.lax.all_to_all(send_le.reshape(ep, r_buf), "expert",
                                     0, 0, tiled=False).reshape(ep * r_buf)

        order2 = jnp.argsort(recv_le, stable=True)
        rows = jnp.take(recv, order2, axis=0)
        group_sizes = jnp.bincount(recv_le, length=n_local).astype(jnp.int32)
        ffn = moe_expert_ffn(rows, wi_gate.astype(dt), wi_up.astype(dt),
                             wo.astype(dt), group_sizes)
        back = jnp.zeros_like(ffn).at[order2].set(ffn)
        back = jax.lax.all_to_all(back.reshape(ep, r_buf, e), "expert", 0, 0,
                                  tiled=False).reshape(ep * r_buf, e)

        row_out = jnp.take(back, slot, axis=0)          # sorted-row results
        w_sorted = jnp.take(w.reshape(-1), order).astype(dt)
        out = jnp.zeros((t_loc, e), dt).at[tok_of_sorted].add(
            row_out * w_sorted[:, None])
        return out.reshape(b, s, e), aux

    tok_spec = P(batch_axes or None, seq_axis, None)
    specs = dict(mesh=mesh,
                 in_specs=(P(), P("expert"), P("expert"), P("expert"),
                           tok_spec),
                 out_specs=(tok_spec, P()))
    if hasattr(jax, "shard_map"):          # jax>=0.8 surface
        fn = jax.shard_map(body, axis_names=manual, **specs)
    else:
        # pre-0.8: manual axes are expressed as the complement (`auto`)
        from jax.experimental.shard_map import shard_map as _sm
        fn = _sm(body, check_rep=False,
                 auto=frozenset(mesh.axis_names) - frozenset(manual),
                 **specs)
    out, aux = fn(params["router"], params["wi_gate"], params["wi_up"],
                  params["wo"], x)
    if cfg.moe_shared_expert_size:
        out = out + _apply_shared_expert(params, x.astype(dt), cfg)
    return out, aux


def apply_moe_mlp(params, x, cfg: TransformerConfig):
    """Dispatch/combine via one-hot einsum (GShard-style, reference
    ``deepspeed/moe/sharded_moe.py:96 MOELayer``). Capacity-bounded, dropless
    within capacity; aux load-balancing loss returned alongside.

    ``moe_impl: "grouped"`` routes to ``apply_moe_grouped`` (sort-by-expert
    + ragged_dot) when the expert mesh axis is unsharded.
    """
    from ..moe.sharded_moe import topk_gating_einsum
    dt = cfg.act_dtype
    b, s, e = x.shape

    if cfg.moe_impl == "grouped":
        from ..utils import groups as _g
        from ..parallel.sharding import current_manual_axes as _cma
        ep = (_g.get_mesh().shape.get("expert", 1)
              if _g.mesh_is_initialized() else 1)
        if ep == 1:
            return apply_moe_grouped(params, x, cfg)
        if not _cma():
            # sharded expert axis: dropless grouped path with an explicit
            # all-to-all ring (cannot nest inside an existing manual region
            # — the ZeRO++ step falls through to the einsum dispatch).
            # Guard the manual region's static divisibility contracts: the
            # einsum dispatch tolerates anything via GSPMD padding, so odd
            # shapes (v1 serving with b=1, ragged expert counts) fall back
            # loudly-documented rather than mis-routing.
            import math as _math
            mesh = _g.get_mesh()
            bsz, slen, _ = x.shape
            bdiv = _math.prod(mesh.shape.get(a, 1) for a in _g.BATCH_AXES)
            sdiv = mesh.shape.get("seq", 1)
            if (cfg.num_experts % ep == 0 and bsz % bdiv == 0
                    and slen % sdiv == 0):
                return apply_moe_grouped_ep(params, x, cfg, mesh)

    # Explicit dispatch/combine layouts (the reference's all-to-all
    # semantics, sharded_moe.py:533 _AllToAll): tokens ride the batch axes,
    # expert buffers ride the expert axis. Without these anchors XLA's
    # propagation can demand embed-sharded activations inside the layer scan
    # (involuntary full rematerialization).
    constrain_tok = lambda t: t
    constrain_exp = lambda t: t
    from ..utils import groups as _groups
    from ..parallel.sharding import current_manual_axes
    if _groups.mesh_is_initialized() and not current_manual_axes():
        mesh = _groups.get_mesh()
        if mesh.devices.size > 1:
            import jax.sharding as _js
            batch_axes = tuple(a for a in _groups.BATCH_AXES
                               if mesh.shape.get(a, 1) > 1) or None
            exp_axis = "expert" if mesh.shape.get("expert", 1) > 1 else None
            tok_sh = _js.NamedSharding(mesh, _js.PartitionSpec(batch_axes, None))
            exp_sh = _js.NamedSharding(
                mesh, _js.PartitionSpec(exp_axis, None, None))
            constrain_tok = lambda t: jax.lax.with_sharding_constraint(t, tok_sh)
            constrain_exp = lambda t: jax.lax.with_sharding_constraint(t, exp_sh)

    tokens = constrain_tok(x.reshape(b * s, e))
    logits = jnp.einsum("te,ex->tx", tokens.astype(jnp.float32),
                        params["router"].astype(jnp.float32))
    combine, dispatch, aux_loss = topk_gating_einsum(
        logits, k=cfg.num_experts_per_tok, capacity_factor=cfg.moe_capacity_factor,
        normalize=cfg.moe_norm_topk)
    # dispatch: (T, X, C) bool → expert inputs (X, C, E); the einsum against
    # batch-sharded tokens with expert-sharded output IS the all-to-all
    expert_in = constrain_exp(jnp.einsum("txc,te->xce", dispatch.astype(dt), tokens))
    g = jnp.einsum("xce,xef->xcf", expert_in, params["wi_gate"].astype(dt))
    u = jnp.einsum("xce,xef->xcf", expert_in, params["wi_up"].astype(dt))
    h = jax.nn.silu(g) * u
    expert_out = constrain_exp(jnp.einsum("xcf,xfe->xce", h, params["wo"].astype(dt)))
    out = constrain_tok(jnp.einsum("txc,xce->te", combine.astype(dt), expert_out))
    if cfg.moe_shared_expert_size:
        out = out + _apply_shared_expert(params, tokens, cfg)
    return out.reshape(b, s, e), aux_loss


# ---- embeddings ---------------------------------------------------------

def init_embeddings(rng, cfg: TransformerConfig):
    r = jax.random.split(rng, 3)
    params = {"tok": _normal(r[0], (cfg.vocab_size, cfg.hidden_size), cfg.p_dtype, 0.02)}
    axes = {"tok": ("vocab", "embed")}
    if cfg.position == "learned":
        params["pos"] = _normal(r[1], (cfg.max_seq_len, cfg.hidden_size), cfg.p_dtype, 0.02)
        axes["pos"] = ("unmodeled", "embed")
    if cfg.type_vocab_size:
        params["type"] = _normal(r[1] if cfg.position != "learned" else
                                 jax.random.fold_in(r[1], 1),
                                 (cfg.type_vocab_size, cfg.hidden_size), cfg.p_dtype, 0.02)
        axes["type"] = ("unmodeled", "embed")
    if cfg.embedding_norm:
        en, en_axes = init_norm(cfg)
        params["emb_norm"] = en
        axes["emb_norm"] = en_axes
    if not cfg.tie_embeddings:
        params["lm_head"] = _normal(r[2], (cfg.hidden_size, cfg.vocab_size), cfg.p_dtype,
                                    cfg.hidden_size ** -0.5)
        axes["lm_head"] = ("embed", "vocab")
        if cfg.lm_head_bias:
            params["lm_head_bias"] = _zeros((cfg.vocab_size,), cfg.p_dtype)
            axes["lm_head_bias"] = ("vocab",)
    return params, axes
