"""Causal transformer LM: the framework's native model family.

Functional design: ``CausalLM(cfg)`` exposes ``init(rng) -> params``,
``apply(params, input_ids, ...) -> logits``, ``loss(params, batch) -> scalar``
and ``logical_axes()`` — a parallel pytree of logical-axis tuples consumed by
``parallel/sharding.py`` to derive ZeRO/TP/EP shardings.

Layers are stacked along a leading "layers" dim and executed with
``lax.scan`` (one compile of one layer regardless of depth — the XLA analog
of the reference's per-layer module loop). Activation checkpointing is
``jax.checkpoint`` on the scan body (reference
``runtime/activation_checkpointing/checkpointing.py:486``).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.logging import logger
from . import layers as L
from .config import TransformerConfig, get_config


def _is_axes_leaf(x):
    return isinstance(x, tuple) and all(isinstance(i, (str, type(None))) for i in x)


def _axes_of(init_fn):
    """Extract the logical-axes tree of an ``init_fn(rng) -> (params, axes)``
    without allocating parameter memory (shapes traced via eval_shape; the
    axes dict escapes through a side channel)."""
    box = []

    def wrapped(rng):
        out = init_fn(rng)
        params, axes = out if isinstance(out, tuple) else (out, {})
        box.append(axes)
        return params

    jax.eval_shape(wrapped, jax.random.PRNGKey(0))
    return box[0]


def _activation_constraint(partition: bool = False):
    """Pin the (B, S, E) scan-carried activation to batch/seq sharding.

    Without this, XLA's sharding propagation can derive an embed-dim
    sharding for the loop carry from ZeRO gradient constraints and emit an
    'involuntary full rematerialization' reshard inside the layer scan."""
    from ..utils import groups
    if not groups.mesh_is_initialized():
        return lambda h: h
    mesh = groups.get_mesh()
    if mesh.devices.size == 1:
        return lambda h: h
    from ..parallel import sharding as shd
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = shd.batch_spec(mesh)
    if partition and mesh.shape.get("tensor", 1) > 1 and spec[1] is None:
        # partitioned activations (reference checkpointing.py:486): the
        # checkpoint-boundary residual IS this scan carry — anchoring its
        # sequence dim to the tensor axis makes XLA STORE each rank's slice
        # and all-gather only on use (forward compute + backward recompute)
        spec = P(spec[0], "tensor", *spec[2:])

    sharding = NamedSharding(mesh, spec)

    def constrain(h):
        # decided at trace time: inside shard_map manual regions (ZeRO++
        # quantized-collective step) sharding constraints on values varying
        # over manual axes are invalid — the anchor is only needed for the
        # plain-SPMD propagation anyway
        if shd.current_manual_axes():
            return h
        return jax.lax.with_sharding_constraint(h, sharding)

    return constrain


def _remat_policy(name: str):
    if name == "full":
        return None  # jax.checkpoint default: save nothing
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if name == "dots_no_batch":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    if name == "dots_offload":
        # the reference's cpu_checkpointing (activation checkpoints parked
        # in host memory, runtime/activation_checkpointing/checkpointing.py
        # partition+cpu variants): matmul outputs are saved but OFFLOADED to
        # pinned host memory, streamed back for the backward — activation
        # residency on device drops to the live layer
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    return None


def layer_plan(cfg):
    """Execution plan for the layer stack (None = homogeneous single scan).

    Heterogeneous stacks (cfg.layer_types, e.g. Qwen2-MoE's interleaved
    dense-MLP layers — reference ``model_implementations/qwen_v2_moe``) are
    compiled as:
      ("periodic", p) — tags repeat with period p (decoder_sparse_step):
        ONE scan over L/p super-layers whose body applies p sublayers; still
        one compiled body regardless of depth.
      ("segments", [(tag, start, length), ...]) — contiguous runs
        (mlp_only_layers prefixes): one scan per run.
    """
    tags = cfg.layer_types
    if tags is None or len(set(tags)) <= 1:
        return None
    n = len(tags)
    # a period must leave >= 2 scan steps (p == n is the fully-unrolled
    # degenerate "period"; contiguous runs handle those stacks better)
    for p in range(2, min(8, n // 2) + 1):
        if n % p == 0 and all(tags[i] == tags[i % p] for i in range(n)):
            return ("periodic", p)
    runs = []
    start = 0
    for i in range(1, n + 1):
        if i == n or tags[i] != tags[start]:
            runs.append((tags[start], start, i - start))
            start = i
    return ("segments", runs)


def layer_groups(cfg):
    """None (homogeneous) or the ordered param groups of the plan:
    [(tag, (layer indices...)), ...] — group i becomes params["layers"]["g{i}"]
    stacked over its indices. Shared by the model and the HF checkpoint
    containers so both lay out the same tree."""
    plan = layer_plan(cfg)
    if plan is None:
        return None
    if plan[0] == "periodic":
        p = plan[1]
        return [(cfg.layer_types[i], tuple(range(i, cfg.num_layers, p)))
                for i in range(p)]
    return [(tag, tuple(range(start, start + ln)))
            for tag, start, ln in plan[1]]


def walk_layer_plan(plan, groups_, layers_params, xs, carry, body, wrap=None):
    """Single driver for the layer-plan walk — train forward, cached decode,
    and the paged serving runner all follow the same three shapes, so the
    group ordering/slicing logic lives exactly once.

    ``plan``/``groups_``: the model's ``layer_plan``/``layer_groups``
    (None = homogeneous). ``layers_params``: the (possibly grouped) stacked
    layer tree. ``xs``: pytree of per-layer inputs with leading axis L in
    ORIGINAL layer order (None leaves pass through). ``body(carry, lp, xs_t,
    tag) -> (carry, ys_t)`` applies one layer (ys_t may be None).
    ``wrap``: optional transform applied to each scan-step function (remat);
    for the periodic plan it wraps the whole super-layer step, matching the
    one-checkpoint-per-scan-step policy of the homogeneous path.

    Returns (carry, ys) with ys leaves stacked back in original layer order.
    """
    wrap = wrap or (lambda f: f)
    if groups_ is None:
        def step(carry, t):
            lp, xs_t = t
            return body(carry, lp, xs_t, None)
        return jax.lax.scan(wrap(step), carry, (layers_params, xs))
    if plan[0] == "periodic":
        p = plan[1]
        xs_rs = jax.tree.map(
            lambda a: a.reshape((a.shape[0] // p, p) + a.shape[1:]), xs)

        def super_step(carry, t):
            groups_t, xs_t = t
            ys = []
            for j, (tag, _) in enumerate(groups_):
                xj = jax.tree.map(lambda a: a[j], xs_t)
                carry, y = body(carry, groups_t[f"g{j}"], xj, tag)
                ys.append(y)
            stacked = (None if ys[0] is None
                       else jax.tree.map(lambda *z: jnp.stack(z), *ys))
            return carry, stacked

        carry, ys = jax.lax.scan(wrap(super_step), carry, (layers_params, xs_rs))
        ys = jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), ys)
        return carry, ys
    # contiguous segments: one scan per run, ys re-concatenated in order
    parts = []
    for gi, (tag, idxs) in enumerate(groups_):
        lo, n = idxs[0], len(idxs)
        xs_seg = jax.tree.map(lambda a: a[lo:lo + n], xs)

        def step(carry, t, _tag=tag):
            lp, xs_t = t
            return body(carry, lp, xs_t, _tag)

        carry, y = jax.lax.scan(wrap(step), carry,
                                (layers_params[f"g{gi}"], xs_seg))
        parts.append(y)
    ys = (None if parts[0] is None
          else jax.tree.map(lambda *z: jnp.concatenate(z), *parts))
    return carry, ys


def lm_head_logits(h, w, transpose, dt, bias=None, softcap=0.0):
    """logits = h @ (w if transpose else w.T) (+ bias): (B, S, E) → (B, S, V).

    ``softcap``: Gemma-2 final_logit_softcapping (cap * tanh(logits/cap))."""
    eq = "bse,ev->bsv" if transpose else "bse,ve->bsv"
    logits = jnp.einsum(eq, h, w.astype(dt))
    if bias is not None:
        logits = logits + bias.astype(logits.dtype)
    if softcap:
        logits = softcap * jnp.tanh(logits / softcap)
    return logits


def masked_token_nll(logits, labels, loss_mask=None):
    """Mean fp32 cross-entropy over (B, S) tokens; loss_mask weights (or
    drops) positions. Avoids materializing a full fp32 log-softmax."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - label_logits
    if loss_mask is None:
        return jnp.mean(nll)
    return jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)


def logit_buffer_bytes(n_tokens, cfg):
    """Size of the (B, S, V) logits the dense loss would materialize —
    the chunked-CE engagement test shared by decoder and encoder heads."""
    return n_tokens * cfg.vocab_size * (2 if cfg.act_dtype != jnp.float32 else 4)


class CausalLM:
    """Decoder-only LM covering GPT-2 / Llama / Mixtral families."""

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg
        self._inv_freq = L.rope_frequencies(cfg) if cfg.position == "rope" else None
        self._plan = layer_plan(cfg)
        self._groups = layer_groups(cfg)

    # -- init --

    def _init_layer(self, rng, layer_type=None):
        cfg = self.cfg
        r_attn, r_mlp = jax.random.split(rng)
        attn, attn_axes = L.init_attention(r_attn, cfg)
        if (cfg.is_moe if layer_type is None else layer_type == "moe"):
            mlp, mlp_axes = L.init_moe_mlp(r_mlp, cfg)
        else:
            mlp, mlp_axes = L.init_mlp(r_mlp, cfg)
        norm1, norm1_axes = L.init_norm(cfg)
        norm2, norm2_axes = L.init_norm(cfg)
        params = {"attn": attn, "mlp": mlp, "norm1": norm1, "norm2": norm2}
        axes = {"attn": attn_axes, "mlp": mlp_axes, "norm1": norm1_axes, "norm2": norm2_axes}
        if cfg.sandwich_norm:   # Gemma-2 post-attn / post-ffw output norms
            for nm in ("norm3", "norm4"):
                params[nm], axes[nm] = L.init_norm(cfg)
        return params, axes

    def init(self, rng):
        cfg = self.cfg
        r_emb, r_layers = jax.random.split(rng)
        emb, _ = L.init_embeddings(r_emb, cfg)
        layer_rngs = jax.random.split(r_layers, cfg.num_layers)
        if self._groups is None:
            per_layer = [self._init_layer(r)[0] for r in layer_rngs]
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
        else:
            stacked = {}
            for gi, (tag, idxs) in enumerate(self._groups):
                per = [self._init_layer(layer_rngs[i], tag)[0] for i in idxs]
                stacked[f"g{gi}"] = jax.tree.map(lambda *xs: jnp.stack(xs), *per)
        out = {"embed": emb, "layers": stacked}
        if not cfg.post_norm:   # post-norm (BERT) normalizes inside each layer
            out["final_norm"] = L.init_norm(cfg)[0]
        return out

    def abstract_params(self):
        """Shape/dtype tree without allocating (for sharded init)."""
        return jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))

    def logical_axes(self):
        """Pytree of logical-axis tuples mirroring ``init``'s output; stacked
        layer params get a leading "layers" axis."""
        cfg = self.cfg
        emb_axes = _axes_of(lambda r: L.init_embeddings(r, cfg))

        def stack_axes(tag=None):
            layer_axes = _axes_of(lambda r: self._init_layer(r, tag))
            return jax.tree.map(lambda a: ("layers",) + a, layer_axes,
                                is_leaf=_is_axes_leaf)

        if self._groups is None:
            stacked_axes = stack_axes()
        else:
            stacked_axes = {f"g{gi}": stack_axes(tag)
                            for gi, (tag, _) in enumerate(self._groups)}
        out = {"embed": emb_axes, "layers": stacked_axes}
        if not cfg.post_norm:
            out["final_norm"] = _axes_of(lambda r: L.init_norm(cfg))
        return out

    # -- forward --

    def _layer_windows(self):
        """(L,)-int32 per-layer window array for mixed local/global patterns
        (GPT-Neo alternation via ``local_attention_every``, Gemma-2's
        even-layers-windowed via an explicit ``window_pattern``), or None
        when layers are homogeneous (uniform windows flow through
        cfg.sliding_window inside apply_attention)."""
        cfg = self.cfg
        if cfg.window_pattern is not None:
            return jnp.asarray(cfg.window_pattern, jnp.int32)
        if cfg.sliding_window is None or not cfg.local_attention_every:
            return None
        n = cfg.local_attention_every
        return jnp.asarray([cfg.sliding_window if i % n == n - 1 else 0
                            for i in range(cfg.num_layers)], jnp.int32)

    def _layer_fn(self, lp, h, positions, segment_ids, attn_bias=None, window=None,
                  layer_type=None):
        cfg = self.cfg
        is_moe = cfg.is_moe if layer_type is None else layer_type == "moe"
        if cfg.act_quant_bits:
            # QAT activation quantization (compression QuantAct analog):
            # the layer input round-trips the int grid, STE backward
            from ..compression.compress import fake_quantize_activation
            h = fake_quantize_activation(h, cfg.act_quant_bits)
        if cfg.post_norm:
            # BERT block: norm AFTER each residual add, attention reads the
            # raw stream
            attn_out, _ = L.apply_attention(lp["attn"], h, cfg, positions=positions,
                                            inv_freq=self._inv_freq,
                                            segment_ids=segment_ids,
                                            attn_bias=attn_bias, window=window)
            h = L.apply_norm(lp["norm1"], h + attn_out, cfg)
            mlp_out = L.apply_mlp(lp["mlp"], h, cfg)
            return L.apply_norm(lp["norm2"], h + mlp_out, cfg), jnp.zeros((), jnp.float32)
        a_in = L.apply_norm(lp["norm1"], h, cfg)
        attn_out, _ = L.apply_attention(lp["attn"], a_in, cfg, positions=positions,
                                        inv_freq=self._inv_freq, segment_ids=segment_ids,
                                        attn_bias=attn_bias, window=window)
        if cfg.sandwich_norm:   # Gemma-2: norm the sublayer OUTPUT pre-residual
            attn_out = L.apply_norm(lp["norm3"], attn_out, cfg)
        if cfg.parallel_block:
            # NeoX/Falcon parallel residual: attn and mlp both read the
            # pre-attention stream; one residual add
            m_in = L.apply_norm(lp["norm2"], h, cfg)
        else:
            h = h + attn_out
            m_in = L.apply_norm(lp["norm2"], h, cfg)
        if is_moe:
            mlp_out, aux = L.apply_moe_mlp(lp["mlp"], m_in, cfg)
        else:
            mlp_out, aux = L.apply_mlp(lp["mlp"], m_in, cfg), jnp.zeros((), jnp.float32)
        if cfg.sandwich_norm:
            mlp_out = L.apply_norm(lp["norm4"], mlp_out, cfg)
        if cfg.parallel_block:
            return h + attn_out + mlp_out, aux
        return h + mlp_out, aux

    def embed_fwd(self, embed_params, input_ids, positions=None, token_type_ids=None):
        """Token (+ learned position, + token-type) embedding lookup:
        (B, S) → (B, S, E)."""
        cfg = self.cfg
        dt = cfg.act_dtype
        h = embed_params["tok"].astype(dt)[input_ids]
        if cfg.embed_scale != 1.0:   # Gemma: sqrt(E), cast like HF's normalizer
            h = h * jnp.asarray(cfg.embed_scale, dt)
        if cfg.position == "learned":
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
            h = h + embed_params["pos"].astype(dt)[positions + cfg.position_offset]
        if cfg.type_vocab_size:   # BERT segment embeddings
            tt = (token_type_ids if token_type_ids is not None
                  else jnp.zeros_like(input_ids))
            h = h + embed_params["type"].astype(dt)[tt]
        if cfg.embedding_norm:   # BLOOM/BERT post-embedding layernorm
            h = L.apply_norm(embed_params["emb_norm"], h, cfg)
        return h

    def head_loss(self, head_params, h, labels, loss_mask=None):
        """Final norm + lm head + cross-entropy from hidden states.

        ``head_params``: {"embed": ..., "final_norm": ...} — the persistent
        (non-layer) params. Used by the ZeRO-Infinity layer-streaming runner
        which never materializes the full param tree on device.
        """
        cfg = self.cfg
        if "final_norm" in head_params:   # absent for post-norm encoders
            h = L.apply_norm(head_params["final_norm"], h, cfg)
        w, transpose = self._lm_head_weight(head_params)
        if (cfg.loss_chunks > 0 and cfg.vocab_size >= 4096
                and logit_buffer_bytes(labels.size, cfg) > cfg.loss_chunk_threshold_bytes):
            from ..ops.cross_entropy import lm_cross_entropy
            return lm_cross_entropy(h, w.astype(h.dtype), labels, loss_mask=loss_mask,
                                    n_chunks=cfg.loss_chunks, transpose_w=transpose,
                                    softcap=cfg.logit_softcap)
        logits = lm_head_logits(h, w, transpose, cfg.act_dtype,
                                softcap=cfg.logit_softcap)
        return masked_token_nll(logits, labels, loss_mask)

    def hidden_states(self, params, input_ids, *, positions=None, segment_ids=None,
                      token_type_ids=None):
        """Embed + layer stack + final norm: (B, S) → ((B, S, E), aux_loss)."""
        cfg = self.cfg
        dt = cfg.act_dtype
        h = self.embed_fwd(params["embed"], input_ids, positions, token_type_ids)
        if cfg.position == "learned" and positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)

        constrain = _activation_constraint(cfg.partition_activations)

        # ALiBi needs no precomputed bias: apply_attention passes the
        # per-head slopes down and the flash kernel builds the term
        # in-kernel; XLA fallbacks expand slopes per layer (cheap next to
        # the O(S^2) attention math they already do).
        attn_bias = None

        windows = self._layer_windows()
        aux0 = jnp.zeros((), jnp.float32)
        # inside a partial-manual shard_map (ZeRO++ quantized-collective
        # step) the MoE aux loss becomes data-varying through the routed
        # dispatch; the scan carry's initial value must match that vma type
        from ..parallel.sharding import current_manual_axes
        manual = current_manual_axes()
        if manual:
            if hasattr(jax.lax, "pcast"):
                aux0 = jax.lax.pcast(aux0, tuple(manual), to="varying")
            else:
                aux0 = jax.lax.pvary(aux0, tuple(manual))
        carry = (h, aux0)

        def make_body(fn):
            return (jax.checkpoint(fn, policy=_remat_policy(cfg.remat))
                    if cfg.remat != "none" else fn)

        def body(carry, lp, win, tag):
            h, aux_sum = carry
            h, aux = self._layer_fn(lp, h, positions, segment_ids, attn_bias,
                                    win, layer_type=tag)
            return (constrain(h), aux_sum + aux), None

        carry, _ = walk_layer_plan(self._plan, self._groups, params["layers"],
                                   windows, carry, body, wrap=make_body)
        h, aux_total = carry
        if not cfg.post_norm:
            h = L.apply_norm(params["final_norm"], h, cfg)
        # average the load-balancing aux over layers that HAVE routers
        # (dense interleave layers contribute 0 and must not dilute it)
        n_moe = sum(1 for i in range(cfg.num_layers)
                    if cfg.layer_type(i) == "moe") or 1
        return h, aux_total / n_moe

    def _lm_head_weight(self, params):
        """Returns (w, transpose): logits = h @ (w.T if not transpose else w)."""
        if self.cfg.tie_embeddings:
            return params["embed"]["tok"], False
        return params["embed"]["lm_head"], True

    def apply(self, params, input_ids, *, positions=None, segment_ids=None,
              return_aux_loss=False):
        """input_ids: (B, S) int32 → logits (B, S, V)."""
        dt = self.cfg.act_dtype
        h, aux_total = self.hidden_states(params, input_ids, positions=positions,
                                          segment_ids=segment_ids)
        w, transpose = self._lm_head_weight(params)
        logits = lm_head_logits(h, w, transpose, dt,
                                bias=params["embed"].get("lm_head_bias"),
                                softcap=self.cfg.logit_softcap)
        if return_aux_loss:
            return logits, aux_total
        return logits

    # -- decode (KV-cache) --

    def init_cache(self, batch_size, max_len, dtype=None):
        """Stacked KV cache: {"k","v"}: (L, B, S_max, KVH, D) — scan-able."""
        cfg = self.cfg
        dt = dtype or cfg.act_dtype
        shape = (cfg.num_layers, batch_size, max_len, cfg.kv_heads, cfg.dims_per_head)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def apply_decode(self, params, input_ids, cache, cache_len):
        """Incremental forward: input_ids (B, S_new); returns (logits, cache).

        ``lax.scan`` zips the stacked layer params with the stacked cache —
        one compiled layer regardless of depth, updated cache as scan ys.
        """
        cfg = self.cfg
        dt = cfg.act_dtype
        b, s = input_ids.shape
        positions = cache_len[:, None] + jnp.arange(s)[None, :]
        h = self.embed_fwd(params["embed"], input_ids, positions)

        attn_bias = None
        if cfg.position == "alibi":
            attn_bias = L.alibi_bias(cfg.num_heads, positions,
                                     jnp.arange(cache["k"].shape[2]))

        windows = self._layer_windows()

        def dec_layer(lp, h, ck, cv, win, tag=None):
            is_moe = cfg.is_moe if tag is None else tag == "moe"
            if cfg.act_quant_bits:   # QAT: decode must match the forward
                from ..compression.compress import fake_quantize_activation
                h = fake_quantize_activation(h, cfg.act_quant_bits)
            a_in = L.apply_norm(lp["norm1"], h, cfg)
            attn_out, kv = L.apply_attention(lp["attn"], a_in, cfg, positions=positions,
                                             inv_freq=self._inv_freq,
                                             kv_cache=(ck, cv), cache_len=cache_len,
                                             attn_bias=attn_bias, window=win)
            if cfg.sandwich_norm:
                attn_out = L.apply_norm(lp["norm3"], attn_out, cfg)
            if cfg.parallel_block:
                m_in = L.apply_norm(lp["norm2"], h, cfg)
            else:
                h = h + attn_out
                m_in = L.apply_norm(lp["norm2"], h, cfg)
            if is_moe:
                mlp_out, _ = L.apply_moe_mlp(lp["mlp"], m_in, cfg)
            else:
                mlp_out = L.apply_mlp(lp["mlp"], m_in, cfg)
            if cfg.sandwich_norm:
                mlp_out = L.apply_norm(lp["norm4"], mlp_out, cfg)
            if cfg.parallel_block:
                return h + attn_out + mlp_out, kv
            return h + mlp_out, kv

        def body(h, lp, xs_t, tag):
            ck, cv, win = xs_t
            return dec_layer(lp, h, ck, cv, win, tag)

        h, (new_k, new_v) = walk_layer_plan(
            self._plan, self._groups, params["layers"],
            (cache["k"], cache["v"], windows), h, body)
        h = L.apply_norm(params["final_norm"], h, cfg)
        w, transpose = self._lm_head_weight(params)
        logits = lm_head_logits(h, w, transpose, dt,
                                bias=params["embed"].get("lm_head_bias"),
                                softcap=cfg.logit_softcap)
        return logits, {"k": new_k, "v": new_v}

    # -- loss --

    def loss(self, params, batch):
        """batch: dict(input_ids (B, S), labels (B, S), optional loss_mask).

        Cross-entropy in fp32 (reference models compute loss in fp32 under
        fp16 training too); adds MoE aux loss when configured.
        """
        cfg = self.cfg
        labels = batch["labels"]
        mask = batch.get("loss_mask")
        # The fused path trades one extra lm-head matmul (bwd recompute) for
        # never materializing (B, S, V): a win only once the logits are
        # actually big. Shapes are static under jit, so decide here.
        if (cfg.loss_chunks > 0 and cfg.vocab_size >= 4096
                and logit_buffer_bytes(batch["input_ids"].size, cfg)
                > cfg.loss_chunk_threshold_bytes):
            # fused vocab-chunked path: the (B, S, V) logits never exist
            from ..ops.cross_entropy import lm_cross_entropy
            h, aux = self.hidden_states(params, batch["input_ids"],
                                        positions=batch.get("positions"),
                                        segment_ids=batch.get("segment_ids"))
            w, transpose = self._lm_head_weight(params)
            loss = lm_cross_entropy(h, w.astype(h.dtype), labels, loss_mask=mask,
                                    n_chunks=cfg.loss_chunks, transpose_w=transpose,
                                    softcap=cfg.logit_softcap)
        else:
            logits, aux = self.apply(params, batch["input_ids"],
                                     positions=batch.get("positions"),
                                     segment_ids=batch.get("segment_ids"),
                                     return_aux_loss=True)
            loss = masked_token_nll(logits, labels, mask)
        if cfg.is_moe:
            loss = loss + cfg.moe_aux_loss_coef * aux
        return loss

    def param_count(self):
        import math
        return sum(math.prod(x.shape) for x in jax.tree.leaves(self.abstract_params()))


def build_model(name_or_cfg, **overrides) -> CausalLM:
    if isinstance(name_or_cfg, str):
        cfg = get_config(name_or_cfg, **overrides)
    elif isinstance(name_or_cfg, TransformerConfig):
        cfg = name_or_cfg.replace(**overrides) if overrides else name_or_cfg
    else:
        raise TypeError(
            f"build_model expects preset name or TransformerConfig, got {type(name_or_cfg)}")
    if cfg.mlm_head or not cfg.causal:
        from .bert import EncoderLM
        return EncoderLM(cfg)
    return CausalLM(cfg)
