"""Model configurations and preset registry.

The reference frameworks ships no model zoo for training (users bring
torch modules) but its benchmark configs name concrete architectures
(BASELINE.md acceptance configs: GPT-2-small, BERT-large, Llama-2-7B,
Mixtral-8x7B, Llama-2-70B). deepspeed_tpu ships a native functional
transformer covering those families; HF models are adapted via
``module_inject`` at inference time.
"""

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None → MHA
    head_dim: Optional[int] = None      # None → hidden_size // num_heads
    intermediate_size: Optional[int] = None  # None → 4x (gelu) / 8/3x rounded (swiglu)
    max_seq_len: int = 4096
    # "swiglu"/"geglu" are gated (silu / tanh-gelu gate); rest are plain MLPs
    activation: str = "swiglu"          # "swiglu" | "geglu" | "gelu" | "gelu_exact" | "relu"
    norm: str = "rmsnorm"               # "rmsnorm" | "layernorm"
    position: str = "rope"              # "rope" | "learned" | "alibi"
    position_offset: int = 0            # learned-position index offset (OPT: 2)
    rope_theta: float = 10000.0
    rotary_pct: float = 1.0             # fraction of head_dim rotated (GPT-NeoX)
    rope_interleaved: bool = False      # GPT-NeoX/GPT-J (cos,sin per pair) layout
    parallel_block: bool = False        # h + attn(ln1 h) + mlp(ln2 h) (NeoX/Falcon)
    norm_eps: float = 1e-5
    embedding_norm: bool = False        # layernorm right after token embed (BLOOM/BERT)
    embed_scale: float = 1.0            # token-embedding multiplier (Gemma: sqrt(E))
    post_norm: bool = False             # norm AFTER residual add (BERT) vs pre-LN
    type_vocab_size: int = 0            # token-type (segment) embeddings (BERT)
    mlm_head: bool = False              # BERT MLM head: dense+gelu+LN+decoder bias
    tie_embeddings: bool = False
    lm_head_bias: bool = False          # biased untied LM head (GPT-J, Phi)
    use_bias: bool = False
    qkv_bias: bool = False              # bias on q/k/v only (Qwen2)
    mlp_bias: Optional[bool] = None     # None → use_bias (GPT-J: mlp-only biases)
    out_bias: Optional[bool] = None     # attention out-proj bias override (GPT-Neo)
    causal: bool = True
    # sliding-window attention: query attends keys in (q-window, q] (Mistral).
    # local_attention_every=N makes every Nth layer (1-indexed remainder 0...
    # i.e. layers with index % N == N-1) windowed and the rest global
    # (GPT-Neo alternates global/local); None with sliding_window set means
    # ALL layers are windowed.
    sliding_window: Optional[int] = None
    local_attention_every: Optional[int] = None
    # explicit per-layer window sizes (len == num_layers, 0 = global) for
    # patterns local_attention_every can't express (Gemma-2 windows the
    # EVEN-indexed layers). Takes precedence over local_attention_every.
    window_pattern: Optional[tuple] = None
    # q/k normalization before rope (HF refs: MPT attn_config.qk_ln,
    # StableLM qk_layernorm, Phi qk_layernorm):
    #   "full":     one norm over the flattened (H*D) q / (KVH*D) k vectors
    #   "head_dim": one (D,) norm shared by all heads
    #   "per_head": separate (H, D) weights per head
    # The norm family follows cfg.norm (all current variants: layernorm).
    qk_norm: Optional[str] = None
    qk_norm_bias: bool = True           # StableLM's per-head LNs are bias-free
    # Gemma-2 block structure: extra norms on each sublayer OUTPUT before
    # the residual add (norm1=input, norm3=post-attn, norm2=pre-ffw,
    # norm4=post-ffw)
    sandwich_norm: bool = False
    attn_softcap: float = 0.0           # tanh softcap on attention logits (Gemma-2)
    logit_softcap: float = 0.0          # tanh softcap on final LM logits (Gemma-2)
    attn_scale: Optional[float] = None  # override 1/sqrt(head_dim) (Gemma-2
                                        # query_pre_attn_scalar ** -0.5)
    # per-layer structure tags for heterogeneous stacks ("dense" | "moe";
    # len == num_layers). None = homogeneous (every layer is MoE iff
    # num_experts > 0). Qwen2-MoE's mlp_only_layers / decoder_sparse_step
    # interleave dense-MLP layers into a routed-expert stack.
    layer_types: Optional[tuple] = None
    # MoE (Mixtral-style; 0 experts → dense)
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coef: float = 0.01
    moe_norm_topk: bool = True          # renormalize top-k gates (Mixtral yes, Qwen2-MoE no)
    moe_shared_expert_size: int = 0     # always-on shared expert width (Qwen2-MoE)
    # "einsum": capacity-bounded one-hot dispatch (GShard/EP all-to-all);
    # "grouped": dropless sort-by-expert + ragged_dot (megablox pattern,
    # expert axis unsharded only)
    moe_impl: str = "einsum"
    # routed-expert FFN width when it differs from the dense-MLP width
    # (Qwen2-MoE: moe_intermediate_size vs intermediate_size); None → ffn_size
    moe_intermediate_size: Optional[int] = None
    # numerics
    dtype: str = "bfloat16"             # activation dtype
    param_dtype: str = "float32"        # stored parameter dtype
    # attention implementation: "auto" | "reference" | "flash" | "ring"
    attn_impl: str = "auto"
    # remat policy for scan-over-layers ("none"|"full"|"dots")
    remat: str = "none"
    # partition saved activations: checkpoint-boundary residuals stored with
    # their SEQUENCE dim sharded over the tensor axis, gathered on use
    # (reference partition_activations, checkpointing.py:486)
    partition_activations: bool = False
    # QAT activation fake-quant bits (compression QuantAct analog): each
    # layer's attention/MLP inputs round-trip an int grid with an STE
    # backward; 0 disables
    act_quant_bits: int = 0
    # vocab-chunked fused cross-entropy (ops/cross_entropy.py): number of
    # lm-head chunks; 0 disables. Engaged when the (B, S, V) logits would
    # exceed loss_chunk_threshold_bytes — the fused path trades one extra
    # lm-head matmul for never materializing the logits.
    loss_chunks: int = 8
    loss_chunk_threshold_bytes: int = 1 << 30

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def ffn_size(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        if self.activation in ("swiglu", "geglu"):
            return ((int(self.hidden_size * 8 / 3) + 255) // 256) * 256
        return 4 * self.hidden_size

    @property
    def act_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def p_dtype(self):
        return jnp.dtype(self.param_dtype)

    @property
    def moe_ffn_size(self) -> int:
        return self.moe_intermediate_size or self.ffn_size

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def layer_type(self, i: int) -> str:
        if self.layer_types is not None:
            return self.layer_types[i]
        return "moe" if self.is_moe else "dense"

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


# ---- preset registry (sizes from the public model cards) ----

PRESETS = {
    # GPT-2 family (learned positions, gelu, layernorm, tied embeddings, biases)
    "gpt2-small": TransformerConfig(vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12,
                                    max_seq_len=1024, activation="gelu", norm="layernorm", position="learned",
                                    tie_embeddings=True, use_bias=True),
    "gpt2-medium": TransformerConfig(vocab_size=50257, hidden_size=1024, num_layers=24, num_heads=16,
                                     max_seq_len=1024, activation="gelu", norm="layernorm", position="learned",
                                     tie_embeddings=True, use_bias=True),
    "gpt2-xl": TransformerConfig(vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25,
                                 max_seq_len=1024, activation="gelu", norm="layernorm", position="learned",
                                 tie_embeddings=True, use_bias=True),
    # Llama-2 family
    "llama2-7b": TransformerConfig(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
                                   intermediate_size=11008, max_seq_len=4096),
    "llama2-13b": TransformerConfig(vocab_size=32000, hidden_size=5120, num_layers=40, num_heads=40,
                                    intermediate_size=13824, max_seq_len=4096),
    "llama2-70b": TransformerConfig(vocab_size=32000, hidden_size=8192, num_layers=80, num_heads=64,
                                    num_kv_heads=8, intermediate_size=28672, max_seq_len=4096),
    "llama3-8b": TransformerConfig(vocab_size=128256, hidden_size=4096, num_layers=32, num_heads=32,
                                   num_kv_heads=8, intermediate_size=14336, max_seq_len=8192,
                                   rope_theta=500000.0),
    # Mixtral MoE
    "mixtral-8x7b": TransformerConfig(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
                                      num_kv_heads=8, intermediate_size=14336, max_seq_len=32768,
                                      rope_theta=1e6, num_experts=8, num_experts_per_tok=2),
    # BLOOM family (ALiBi positions, embedding layernorm, gelu, biases)
    "bloom-560m": TransformerConfig(vocab_size=250880, hidden_size=1024, num_layers=24, num_heads=16,
                                    max_seq_len=2048, activation="gelu", norm="layernorm",
                                    position="alibi", embedding_norm=True, tie_embeddings=True,
                                    use_bias=True),
    "bloom-7b1": TransformerConfig(vocab_size=250880, hidden_size=4096, num_layers=30, num_heads=32,
                                   max_seq_len=2048, activation="gelu", norm="layernorm",
                                   position="alibi", embedding_norm=True, tie_embeddings=True,
                                   use_bias=True),
    # Falcon-7B (multi-query attention, parallel block, one shared norm)
    "falcon-7b": TransformerConfig(vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
                                   num_kv_heads=1, intermediate_size=18176, max_seq_len=2048,
                                   activation="gelu_exact", norm="layernorm", parallel_block=True,
                                   tie_embeddings=True),
    # GPT-J-6B (interleaved partial rotary, parallel block, MLP-only biases)
    "gptj-6b": TransformerConfig(vocab_size=50400, hidden_size=4096, num_layers=28, num_heads=16,
                                 intermediate_size=16384, max_seq_len=2048, activation="gelu",
                                 norm="layernorm", rotary_pct=64 / 256, rope_interleaved=True,
                                 parallel_block=True, mlp_bias=True),
    # GPT-NeoX-20B / Pythia family (partial rotary, parallel residual)
    "gpt-neox-20b": TransformerConfig(vocab_size=50432, hidden_size=6144, num_layers=44, num_heads=64,
                                      intermediate_size=24576, max_seq_len=2048,
                                      activation="gelu_exact", norm="layernorm", rotary_pct=0.25,
                                      parallel_block=True, use_bias=True),
    # MPT-7B (ALiBi, bias-free, exact gelu)
    "mpt-7b": TransformerConfig(vocab_size=50368, hidden_size=4096, num_layers=32, num_heads=32,
                                intermediate_size=16384, max_seq_len=2048, activation="gelu_exact",
                                norm="layernorm", position="alibi", tie_embeddings=True),
    # Gemma-7B (GeGLU, sqrt(E)-scaled embeddings, wide head_dim)
    "gemma-7b": TransformerConfig(vocab_size=256000, hidden_size=3072, num_layers=28, num_heads=16,
                                  head_dim=256, intermediate_size=24576, max_seq_len=8192,
                                  activation="geglu", embed_scale=3072.0 ** 0.5,
                                  tie_embeddings=True, norm_eps=1e-6),
    # Qwen2-7B (GQA + qkv biases)
    "qwen2-7b": TransformerConfig(vocab_size=152064, hidden_size=3584, num_layers=28, num_heads=28,
                                  num_kv_heads=4, intermediate_size=18944, max_seq_len=32768,
                                  rope_theta=1e6, qkv_bias=True, norm_eps=1e-6),
    # Phi-2 (parallel block sharing one layernorm, partial rotary, biases)
    "phi-2": TransformerConfig(vocab_size=51200, hidden_size=2560, num_layers=32, num_heads=32,
                               intermediate_size=10240, max_seq_len=2048, activation="gelu",
                               norm="layernorm", position="rope", rotary_pct=0.4,
                               parallel_block=True, use_bias=True),
    # Mistral-7B (GQA + sliding-window attention)
    "mistral-7b": TransformerConfig(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
                                    num_kv_heads=8, intermediate_size=14336, max_seq_len=32768,
                                    sliding_window=4096),
    # BERT family (post-norm encoder, MLM head; acceptance config 2 trains
    # bert-large under ZeRO-1/2)
    "bert-base": TransformerConfig(vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
                                   intermediate_size=3072, max_seq_len=512, type_vocab_size=2,
                                   activation="gelu_exact", norm="layernorm", position="learned",
                                   post_norm=True, causal=False, embedding_norm=True,
                                   mlm_head=True, use_bias=True, tie_embeddings=True,
                                   norm_eps=1e-12),
    "bert-large": TransformerConfig(vocab_size=30522, hidden_size=1024, num_layers=24, num_heads=16,
                                    intermediate_size=4096, max_seq_len=512, type_vocab_size=2,
                                    activation="gelu_exact", norm="layernorm", position="learned",
                                    post_norm=True, causal=False, embedding_norm=True,
                                    mlm_head=True, use_bias=True, tie_embeddings=True,
                                    norm_eps=1e-12),
    # tiny variants for tests / CI
    "tiny": TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                              intermediate_size=128, max_seq_len=128, param_dtype="float32",
                              dtype="float32"),
    "tiny-gpt2": TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                                   intermediate_size=256, max_seq_len=128, activation="gelu",
                                   norm="layernorm", position="learned", tie_embeddings=True,
                                   use_bias=True, dtype="float32"),
    "tiny-moe": TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                                  intermediate_size=128, max_seq_len=128, num_experts=4,
                                  num_experts_per_tok=2, dtype="float32"),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    if name not in PRESETS:
        raise KeyError(f"Unknown model preset {name!r}; available: {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return cfg.replace(**overrides) if overrides else cfg
