"""Flops profiler.

Analog of ``deepspeed/profiling/flops_profiler/profiler.py:28``
(FlopsProfiler). The reference monkey-patches torch functionals to count
MACs; under XLA the compiler already knows: ``jit(fn).lower().compile()
.cost_analysis()`` reports flops/bytes for the exact compiled program — no
patching, and it reflects post-fusion reality rather than op-by-op math.
Analytic per-component estimates are also provided for model planning
(``get_model_profile`` parity).
"""

import time
from typing import Any, Callable, Dict, Optional

import jax

from ...models.config import TransformerConfig
from ...utils.logging import logger


def _fmt(n, units=(("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3))):
    for suffix, scale in units:
        if abs(n) >= scale:
            return f"{n / scale:.2f} {suffix}"
    return f"{n:.2f} "


class FlopsProfiler:
    """Measure compiled-program cost + wall clock for any jittable step."""

    def __init__(self, model=None, ds_engine=None):
        self.model = model
        self.ds_engine = ds_engine
        self._cost: Optional[Dict[str, Any]] = None
        self._elapsed = None

    def profile_fn(self, fn: Callable, *args, run: bool = True, **kwargs):
        """Compile ``fn`` and read XLA's cost analysis; optionally execute for
        wall-clock."""
        lowered = jax.jit(fn).lower(*args, **kwargs)
        compiled = lowered.compile()
        cost = compiled.cost_analysis() or {}
        self._cost = cost
        if run:
            t0 = time.perf_counter()
            out = compiled(*args, **kwargs)
            jax.block_until_ready(out)
            self._elapsed = time.perf_counter() - t0
        return cost

    def get_total_flops(self, as_string=False):
        flops = float((self._cost or {}).get("flops", 0.0))
        return _fmt(flops) + "FLOPs" if as_string else flops

    def get_total_bytes(self, as_string=False):
        b = float((self._cost or {}).get("bytes accessed", 0.0))
        return _fmt(b) + "B" if as_string else b

    def get_total_duration(self, as_string=False):
        d = self._elapsed or 0.0
        return f"{d * 1e3:.2f} ms" if as_string else d

    def get_flops_per_sec(self, as_string=False):
        if not self._elapsed:
            return 0.0
        f = self.get_total_flops() / self._elapsed
        return _fmt(f) + "FLOPS" if as_string else f

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        lines = [
            "-" * 60,
            "DeepSpeed-TPU Flops Profiler",
            "-" * 60,
            f"flops (compiled):      {self.get_total_flops(True)}",
            f"bytes accessed:        {self.get_total_bytes(True)}",
            f"wall clock:            {self.get_total_duration(True)}",
            f"achieved:              {self.get_flops_per_sec(True)}",
        ]
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text)
        else:
            logger.info("\n" + text)
        return text

    # -- engine hooks (reference engine.py:1850 start/stop at profile_step) --

    def start_profile(self, ignore_list=None):
        self._t0 = time.perf_counter()

    def stop_profile(self):
        self._elapsed = time.perf_counter() - getattr(self, "_t0", time.perf_counter())

    def end_profile(self):
        pass


def transformer_flops(cfg: TransformerConfig, batch: int, seq: int,
                      training: bool = True) -> Dict[str, float]:
    """Analytic per-step flops (get_model_profile parity): 6·P·T for training
    plus attention O(S²) term."""
    p = _param_count(cfg)
    tokens = batch * seq
    mult = 3 if training else 1  # fwd + 2x bwd
    dense = 2 * p * tokens * mult
    attn = mult * 2 * 2 * batch * cfg.num_layers * cfg.num_heads * seq * seq * cfg.dims_per_head
    return {"params": p, "dense_flops": dense, "attention_flops": attn,
            "total_flops": dense + attn}


def _param_count(cfg: TransformerConfig) -> int:
    e, f, v, l = cfg.hidden_size, cfg.ffn_size, cfg.vocab_size, cfg.num_layers
    h, kvh, d = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head
    attn = e * h * d + 2 * e * kvh * d + h * d * e
    mlp = 3 * e * f if cfg.activation == "swiglu" else 2 * e * f
    if cfg.is_moe:
        mlp = cfg.num_experts * 3 * e * f + e * cfg.num_experts
    emb = v * e * (1 if cfg.tie_embeddings else 2)
    return l * (attn + mlp + 2 * e) + emb + e


def get_model_profile(model, input_shape=None, args=(), kwargs=None, print_profile=True,
                      detailed=True, module_depth=-1, top_modules=1, warm_up=1,
                      as_string=True, output_file=None, ignore_modules=None):
    """Reference-named convenience (flops_profiler API)."""
    import jax.numpy as jnp
    from ...models.transformer import CausalLM
    prof = FlopsProfiler(model)
    if isinstance(model, CausalLM):
        b, s = input_shape or (1, model.cfg.max_seq_len)
        params = model.init(jax.random.PRNGKey(0))
        ids = jnp.zeros((b, s), jnp.int32)
        prof.profile_fn(model.apply, params, ids, run=False)
        flops = prof.get_total_flops(as_string)
        n_params = model.param_count()
        if print_profile:
            prof.print_model_profile(output_file=output_file)
        return flops, None, (_fmt(n_params) if as_string else n_params)
    raise TypeError("get_model_profile expects a CausalLM")
