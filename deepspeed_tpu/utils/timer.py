"""Wall-clock and throughput timers.

TPU-native analog of ``deepspeed/utils/timer.py`` (SynchronizedWallClockTimer,
ThroughputTimer). Synchronization uses ``jax.block_until_ready`` on a tiny
device computation instead of CUDA events: XLA executions are asynchronously
dispatched exactly like CUDA streams, so a fence is required for honest timing.
"""

import time

import jax
import jax.numpy as jnp

from .logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _device_fence():
    """Block until all outstanding device work is complete."""
    try:
        jax.block_until_ready(jnp.zeros((), dtype=jnp.float32) + 0)
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named timer group with device synchronization before reads."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.start_time = 0.0
            self.elapsed_ = 0.0
            self.count = 0

        def start(self, sync=True):
            if self.started_:
                return
            if sync:
                _device_fence()
            self.start_time = time.perf_counter()
            self.started_ = True

        def stop(self, sync=True, record=True):
            if not self.started_:
                return
            if sync:
                _device_fence()
            if record:
                self.elapsed_ += time.perf_counter() - self.start_time
                self.count += 1
            self.started_ = False

        def reset(self):
            self.started_ = False
            self.elapsed_ = 0.0
            self.count = 0

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop()
            elapsed = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

        def mean(self):
            return self.elapsed_ / max(self.count, 1)

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def get_timers(self):
        return self.timers

    def log(self, names, normalizer=1.0, reset=True, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                means[name] = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
        return means


class ThroughputTimer:
    """Tracks samples/sec and (optionally) TFLOPS across train batches.

    Analog of ``deepspeed/utils/timer.py:199``.
    """

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            # No device fence here: XLA dispatch is async and a per-step
            # fence would serialize host and device (very costly on remote
            # platforms). Over a window of steps the steady-state wall time
            # between start/stop pairs converges to true step time.
            self.start_time = time.perf_counter()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step and report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, RunningAvgSamplesPerSec={self.avg_samples_per_sec():.6g}, "
                    f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.6g}")
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step:
            samples_per_step = self.batch_size
            total_step_offset = self.global_step_count - self.start_step
            avg_time_per_step = self.total_elapsed_time / max(total_step_offset, 1)
            return samples_per_step / max(avg_time_per_step, 1e-12)
        return float("-inf")


class NoopTimer:
    class Timer:
        def start(self, **kw):
            ...

        def stop(self, **kw):
            ...

        def reset(self):
            ...

        def elapsed(self, **kw):
            return 0.0

    def __call__(self, name):
        return self.Timer()

    def get_timers(self):
        return {}

    def log(self, *args, **kwargs):
        ...

    def get_mean(self, *args, **kwargs):
        return {}
