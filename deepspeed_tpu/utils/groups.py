"""Device-mesh topology and parallel-group accessors.

TPU-native analog of ``deepspeed/utils/groups.py`` + ``runtime/pipe/topology.py``.
The reference builds torch.distributed process groups for dp/tp/pp/ep/sp; on TPU
the single source of truth is a ``jax.sharding.Mesh`` whose named axes play the
role of process groups:

  axis       role                                  reference analog
  ---------  ------------------------------------  -----------------------------
  pipe       pipeline stages (p2p via ppermute)    PipelineParallelGrid
  zrep       ZeRO replication (MiCS groups / hpZ)  mics.py shard groups,
                                                   groups.py:529 hpZ secondary
  data       data parallel / ZeRO sharding         _get_data_parallel_group
  expert     expert parallel (MoE all-to-all)      _get_expert_parallel_group
  seq        sequence parallel (Ulysses/ring)      _get_sequence_parallel_group
  tensor     tensor (model) parallel               _get_model_parallel_group

``zrep`` (default size 1) factors the data-parallel world into replication
groups: batch shards over zrep×data, but ZeRO param sharding uses only the
inner ``data`` axis — params are sharded 1/k within a group and replicated
across groups, so their allgather rides fast intra-group links while the
gradient reduction becomes reduce-scatter(data) + all-reduce(zrep), the MiCS
hierarchical schedule (reference ``runtime/zero/mics.py:64,357``). With hpZ,
optimizer state additionally shards over zrep (1/N primary partition) while
params keep the 1/k secondary partition (reference
``partition_parameters.py:1653`` _partition_param_sec).

Axis order is outermost→innermost = slowest→fastest links: pipe and data ride
DCN across slices, seq/expert/tensor ride ICI. ZeRO state shards over the
combined ("data","expert","seq") axes (the reference likewise shards ZeRO over
the dp×sp product when Ulysses is active).

All axes always exist (size-1 axes are free in XLA), so PartitionSpecs are
uniform across configurations.
"""

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .logging import logger

MESH_AXIS_ORDER = ("pipe", "zrep", "data", "expert", "seq", "tensor")

# Axes whose product forms the data-parallel world used for ZeRO sharding and
# batch distribution (seq participates in ZeRO sharding but shards the sequence
# dim of the batch, not the batch dim). zrep deliberately NOT in ZERO_AXES:
# params replicate across zrep groups (MiCS/hpZ secondary partition).
ZERO_AXES = ("data", "expert", "seq")
BATCH_AXES = ("zrep", "data", "expert")

_MESH: Optional[Mesh] = None


class MeshBuildError(Exception):
    pass


def build_mesh(mesh_config=None,
               devices: Optional[Sequence] = None,
               data: int = -1,
               tensor: int = 1,
               pipe: int = 1,
               seq: int = 1,
               expert: int = 1,
               zrep: int = 1) -> Mesh:
    """Construct the global device mesh.

    ``data=-1`` (or "auto") fills with whatever devices remain after the other
    axes are carved out. ``zrep`` carves ZeRO replication groups out of the
    data-parallel world (MiCS / hpZ; see module docstring).
    """
    if mesh_config is not None:
        data = mesh_config.data if not isinstance(mesh_config.data, str) else -1
        tensor, pipe, seq, expert = (mesh_config.tensor, mesh_config.pipe, mesh_config.seq, mesh_config.expert)
        zrep = getattr(mesh_config, "zrep", 1)
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = tensor * pipe * seq * expert * zrep
    if data in (-1, None):
        if n % fixed != 0:
            raise MeshBuildError(f"{n} devices not divisible by tensor*pipe*seq*expert*zrep={fixed}")
        data = n // fixed
    total = data * fixed
    if total != n:
        raise MeshBuildError(f"Mesh axes product {total} != device count {n} "
                             f"(pipe={pipe}, zrep={zrep}, data={data}, expert={expert}, "
                             f"seq={seq}, tensor={tensor})")
    sizes = dict(pipe=pipe, zrep=zrep, data=data, expert=expert, seq=seq, tensor=tensor)
    shape = tuple(sizes[a] for a in MESH_AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXIS_ORDER)


def set_mesh(mesh: Mesh):
    global _MESH
    _MESH = mesh
    return mesh


def get_mesh() -> Mesh:
    global _MESH
    if _MESH is None:
        _MESH = build_mesh()
        logger.info(f"Auto-initialized mesh: {dict(zip(_MESH.axis_names, _MESH.devices.shape))}")
    return _MESH


def mesh_is_initialized() -> bool:
    return _MESH is not None


_RESET_HOOKS = []


def register_reset_hook(fn):
    """Caches keyed on the live mesh (compiled rings etc.) register a
    clearer here so reset_mesh() drops them with the mesh — long-lived
    processes that rebuild meshes (elastic rejoin, test loops) must not
    leak executables compiled for dead meshes (advisor r4)."""
    _RESET_HOOKS.append(fn)


def reset_mesh():
    global _MESH
    _MESH = None
    for fn in _RESET_HOOKS:
        fn()


def _axis_size(name: str) -> int:
    mesh = get_mesh()
    return mesh.shape[name]


# ---- world sizes (reference: utils/groups.py accessors) ----

def get_world_size() -> int:
    return math.prod(get_mesh().devices.shape)

def get_data_parallel_world_size() -> int:
    return math.prod(_axis_size(a) for a in BATCH_AXES)

def get_zero_world_size() -> int:
    return math.prod(_axis_size(a) for a in ZERO_AXES)

def get_model_parallel_world_size() -> int:
    return _axis_size("tensor")

get_tensor_model_parallel_world_size = get_model_parallel_world_size

def get_pipe_parallel_world_size() -> int:
    return _axis_size("pipe")

def get_sequence_parallel_world_size() -> int:
    return _axis_size("seq")

def get_expert_parallel_world_size(group_name: str = "default") -> int:
    return _axis_size("expert")

def get_expert_data_parallel_world_size(group_name: str = "default") -> int:
    return get_data_parallel_world_size() // get_expert_parallel_world_size()

def sequence_parallel_is_initialized() -> bool:
    return mesh_is_initialized() and get_sequence_parallel_world_size() > 1

def get_data_parallel_group():
    """Returns the mesh axis names forming the data-parallel 'group'."""
    return BATCH_AXES

def get_model_parallel_group():
    return ("tensor",)

def get_sequence_parallel_group():
    return ("seq",)

def get_expert_parallel_group(group_name: str = "default"):
    return ("expert",)

def get_pipe_parallel_group():
    return ("pipe",)


# ---- sharding helpers ----

def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(get_mesh(), P(*spec))

def replicated_sharding() -> NamedSharding:
    return NamedSharding(get_mesh(), P())

def batch_sharding() -> NamedSharding:
    """Shard the leading (batch) dim over the data-like axes."""
    return NamedSharding(get_mesh(), P(BATCH_AXES))


class ProcessTopology:
    """Cartesian rank↔coordinate mapping over named axes.

    Analog of ``runtime/pipe/topology.py:12``. On TPU the mesh already encodes
    this; kept for API parity and for the launcher/checkpoint layers that
    reason about ranks without a live mesh.
    """

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        import itertools
        from collections import namedtuple
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        for global_rank, coord in enumerate(itertools.product(*ranges)):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs):
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {coord_kwargs} not in topology"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data",), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that vary only along ``axis`` (i.e. its comm groups)."""
        if axis not in self.axes:
            return []
        import itertools
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for combo in itertools.product(*ranges):
            fixed = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**{axis: i}, **fixed) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        def _match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return [rank for coord, rank in self.mapping.items() if _match(coord)]

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeModelDataParallelTopology(ProcessTopology):
    """Analog of ``runtime/pipe/topology.py:244``."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipeDataParallelTopology(ProcessTopology):
    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])
