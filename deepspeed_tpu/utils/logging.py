"""Rank-aware logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (log_dist,
logger setup). Rank filtering uses the JAX process index instead of
torch.distributed ranks.
"""

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name="DeepSpeedTPU", level=logging.INFO):
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
                datefmt="%Y-%m-%d %H:%M:%S",
            ))
        logger_.addHandler(handler)
    return logger_


level = LOG_LEVELS.get(os.environ.get("DS_TPU_LOG_LEVEL", "info").lower(), logging.INFO)
logger = _create_logger(level=level)


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def should_log_on_rank(ranks=None):
    """True if this process should log for the given rank filter (None = rank 0 only
    by convention of the reference's log_dist; [-1] = all ranks)."""
    if ranks is None:
        ranks = [0]
    if -1 in ranks:
        return True
    return _process_index() in ranks


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the processes listed in ``ranks``.

    Mirrors the reference API: ranks=None → rank 0; ranks=[-1] → all ranks.
    """
    if should_log_on_rank(ranks):
        logger.log(level, f"[Rank {_process_index()}] {message}")


def print_rank_0(message):
    if _process_index() == 0:
        print(message, flush=True)


def warning_once(message, _seen=set()):
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
