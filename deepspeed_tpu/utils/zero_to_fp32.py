"""Consolidate a checkpoint into a single fp32 state dict.

Analog of ``deepspeed/utils/zero_to_fp32.py`` (shipped inside every reference
checkpoint dir, ``engine.py:3509``): offline conversion of a saved checkpoint
into a flat {name: fp32 ndarray} mapping usable without the framework. Orbax
checkpoints already store logical arrays, so consolidation = load + cast +
flatten; also callable as a script:

    python -m deepspeed_tpu.utils.zero_to_fp32 <ckpt_dir> <out.npz>
"""

import json
import os
import sys
from typing import Dict

import numpy as np

from .logging import logger


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = tree
    return out


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str, tag=None) -> Dict[str, np.ndarray]:
    """Load <dir>/<tag or latest>/ and return {param_path: fp32 array}."""
    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
        else:
            tag = ""
    path = os.path.join(checkpoint_dir, str(tag)) if tag else checkpoint_dir

    state = None
    if os.path.isfile(os.path.join(path, "state.npz")):
        from ..runtime.checkpoint_engine.orbax_engine import NumpyCheckpointEngine
        state = NumpyCheckpointEngine().load(path)
        module = state["module"]
    else:
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        state = ckptr.restore(os.path.abspath(path))
        module = state["module"]
    flat = _flatten(module)
    out = {k: np.asarray(v, dtype=np.float32) for k, v in flat.items()}
    # Prefer the optimizer's fp32 master weights when present (reference
    # zero_to_fp32 reconstructs fp32 from the ZeRO optimizer shards, not the
    # low-precision model weights).
    opt = state.get("optimizer") if isinstance(state, dict) else None
    if opt and isinstance(opt, dict) and "slots" in opt:
        masters = {k[:-len(".master")]: v
                   for k, v in _flatten(opt["slots"]).items()
                   if k.endswith(".master")}
        for k, v in masters.items():
            if k in out:
                out[k] = np.asarray(v, dtype=np.float32)
    return out


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str, output_file: str,
                                               tag=None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(v.size for v in sd.values())
    logger.info(f"saved {len(sd)} tensors / {total / 1e6:.1f}M params → {output_file}")
    return output_file


def main():
    if len(sys.argv) != 3:
        print(__doc__)
        sys.exit(1)
    convert_zero_checkpoint_to_fp32_state_dict(sys.argv[1], sys.argv[2])


if __name__ == "__main__":
    main()
