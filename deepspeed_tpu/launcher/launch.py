"""Per-node process spawner.

Analog of ``deepspeed/launcher/launch.py`` (``main:133``): spawns ``nproc``
worker processes with RANK/LOCAL_RANK/WORLD_SIZE set from the env the runner
exported; workers call ``deepspeed_tpu.init_distributed`` which feeds those
into ``jax.distributed.initialize``.

Failure semantics match the reference spawner: any worker exiting non-zero
kills the remaining workers (SIGTERM, then SIGKILL after a grace period),
signals received by the spawner propagate to the whole group, and per-rank
logs can be redirected with ``--log-dir`` (reference ``launch.py:133``
signal handling + per-rank output files).
"""

import argparse
import os
import signal
import subprocess
import sys
import time


def _terminate(procs, grace_s: float = 5.0):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_s
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--nproc", type=int, default=1)
    parser.add_argument("--log-dir", type=str, default=None,
                        help="write each rank's stdout/stderr to <dir>/rank<N>.log")
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    rank_offset = int(os.environ.get("RANK_OFFSET", 0))
    procs = []
    logs = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local_rank in range(args.nproc):
        env = dict(os.environ)
        env["LOCAL_RANK"] = str(local_rank)
        env["RANK"] = str(rank_offset + local_rank)
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir, f"rank{env['RANK']}.log"), "w")
            logs.append(out)
        procs.append(subprocess.Popen([sys.executable, args.script] + args.script_args,
                                      env=env, stdout=out, stderr=out))

    def handle(signum, _frame):
        _terminate(procs)
        sys.exit(128 + signum)

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, handle)

    # monitor: first non-zero exit tears down the group (reference behavior
    # — a dead rank would otherwise hang the collective world)
    rc = 0
    live = list(procs)
    try:
        while live:
            for p in list(live):
                ret = p.poll()
                if ret is None:
                    continue
                live.remove(p)
                if ret != 0:
                    sys.stderr.write(
                        f"[launch] rank process pid={p.pid} exited with {ret}; "
                        f"terminating remaining {len(live)} worker(s)\n")
                    _terminate(live)
                    return ret
                rc |= ret
            time.sleep(0.2)
    finally:
        for f in logs:
            f.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
