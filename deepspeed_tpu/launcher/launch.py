"""Per-node process spawner.

Analog of ``deepspeed/launcher/launch.py`` (``main:133``): spawns ``nproc``
worker processes with RANK/LOCAL_RANK/WORLD_SIZE set from the env the runner
exported; workers call ``deepspeed_tpu.init_distributed`` which feeds those
into ``jax.distributed.initialize``.
"""

import argparse
import os
import subprocess
import sys


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--nproc", type=int, default=1)
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    rank_offset = int(os.environ.get("RANK_OFFSET", 0))
    procs = []
    for local_rank in range(args.nproc):
        env = dict(os.environ)
        env["LOCAL_RANK"] = str(local_rank)
        env["RANK"] = str(rank_offset + local_rank)
        procs.append(subprocess.Popen([sys.executable, args.script] + args.script_args,
                                      env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
