"""Multi-host launcher CLI.

Analog of ``deepspeed/launcher/runner.py`` (``main:398``, hostfile parsing
``:210-255``, ``--include/--exclude`` filters ``:265``) and
``multinode_runner.py``. Differences from the reference are TPU-shaped:
worker processes rendezvous through ``jax.distributed`` (coordinator address
= first host) instead of torch.distributed; on Cloud TPU pods the runtime
discovers peers via metadata, so the launcher's job is mostly env setup +
fan-out (pdsh / ssh / mpirun / local).

Usage:
    dstpu --hostfile hosts.txt [--include w1@host1] train.py --args
    dstpu --num_nodes 1 --num_gpus 8 train.py        # local spawn
"""

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "JAX_PLATFORMS", "TPU_CHIPS_PER_HOST_BOUNDS"]


def parse_hostfile(path):
    """'hostname slots=N' lines → OrderedDict host → slots (reference :210)."""
    resource_pool = OrderedDict()
    if not os.path.isfile(path):
        raise FileNotFoundError(f"hostfile {path} not found")
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in resource_pool:
                raise ValueError(f"duplicate host {host} in hostfile")
            resource_pool[host] = slots
    return resource_pool


def parse_inclusion_exclusion(resource_pool, include_str="", exclude_str=""):
    """'host1@host2:0,2' filters (reference :265)."""

    def parse_filter(s):
        mapping = {}
        for item in (s or "").split("@"):
            item = item.strip()
            if not item:
                continue
            if ":" in item:
                host, slots = item.split(":")
                mapping[host] = [int(x) for x in slots.split(",")]
            else:
                mapping[item] = None
        return mapping

    include = parse_filter(include_str)
    exclude = parse_filter(exclude_str)
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")

    active = OrderedDict()
    if include:
        for host, slots in include.items():
            if host not in resource_pool:
                raise ValueError(f"included host {host} not in hostfile")
            n = resource_pool[host]
            active[host] = slots if slots is not None else list(range(n))
    else:
        for host, n in resource_pool.items():
            all_slots = list(range(n))
            if host in exclude:
                drop = exclude[host]
                if drop is None:
                    continue
                all_slots = [s for s in all_slots if s not in drop]
            if all_slots:
                active[host] = all_slots
    return active


def encode_world_info(active_resources):
    import base64
    import json
    return base64.urlsafe_b64encode(json.dumps(active_resources).encode()).decode()


def build_launch_cmds(args, active_resources, user_script, user_args):
    """One command per node (pdsh/ssh fan-out or local exec)."""
    hosts = list(active_resources)
    master = args.master_addr or hosts[0]
    world_size = sum(len(s) for s in active_resources.values())
    cmds = []
    rank_offset = 0
    for host, slots in active_resources.items():
        env = {
            "MASTER_ADDR": master,
            "MASTER_PORT": str(args.master_port),
            "WORLD_SIZE": str(world_size),
            "NNODES": str(len(hosts)),
            "NODE_RANK": str(hosts.index(host)),
            "RANK_OFFSET": str(rank_offset),
        }
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        for k in EXPORT_ENVS:
            if k in os.environ:
                exports += f" {k}={shlex.quote(os.environ[k])}"
        launch = (f"{exports} {sys.executable} -m deepspeed_tpu.launcher.launch "
                  f"--nproc {len(slots)} {shlex.quote(user_script)} "
                  + " ".join(shlex.quote(a) for a in user_args))
        cmds.append((host, launch))
        rank_offset += len(slots)
    return cmds


def main(argv=None):
    parser = argparse.ArgumentParser(description="deepspeed_tpu launcher")
    parser.add_argument("--hostfile", type=str, default=DLTS_HOSTFILE)
    parser.add_argument("--include", type=str, default="")
    parser.add_argument("--exclude", type=str, default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1, dest="num_gpus")
    parser.add_argument("--master_addr", type=str, default=None)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "ssh", "openmpi", "mpich", "slurm", "local"])
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--dry_run", action="store_true",
                        help="print the per-node commands without executing")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if os.path.isfile(args.hostfile):
        pool = parse_hostfile(args.hostfile)
    else:
        n = args.num_gpus if args.num_gpus > 0 else 1
        pool = OrderedDict([("localhost", n)])
    active = parse_inclusion_exclusion(pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    cmds = build_launch_cmds(args, active, args.user_script, args.user_args)

    if args.dry_run:
        for host, cmd in cmds:
            print(f"[{host}] {cmd}")
        return 0

    if len(cmds) == 1 and list(active)[0] == "localhost":
        host, cmd = cmds[0]
        return subprocess.call(cmd, shell=True)

    from .multinode_runner import build_runner
    runner = build_runner(args.launcher if args.launcher != "local" else "ssh",
                          args, active)
    if not runner.backend_exists():
        logger.warning(f"{args.launcher} not found on PATH; commands would be:")
        for c in runner.get_cmd(cmds):
            logger.warning(f"  {c}")
        return 1
    procs = [subprocess.Popen(full, shell=True) for full in runner.get_cmd(cmds)]
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
