"""Multi-node fan-out runners.

Analog of ``deepspeed/launcher/multinode_runner.py:18-376`` (MultiNodeRunner
ABC + PDSH/OpenMPI/MPICH/Slurm/MVAPICH runners): each runner turns the
per-node launch commands the runner CLI builds into the transport-specific
invocation. TPU pods usually launch via the hostfile/ssh path (GCE) — the
MPI/Slurm variants cover clusters fronted by those schedulers.
"""

import os
import shlex
import shutil
from typing import Dict, List, Tuple


class MultiNodeRunner:
    name = "base"

    def __init__(self, args, world_info: Dict[str, List[int]]):
        self.args = args
        self.world_info = world_info   # host -> slot list

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, per_node_cmds: List[Tuple[str, str]]) -> List[str]:
        """per_node_cmds: [(host, shell command)] → commands to exec."""
        raise NotImplementedError

    @property
    def num_nodes(self):
        return len(self.world_info)

    @property
    def total_slots(self):
        return sum(len(s) for s in self.world_info.values())


class PDSHRunner(MultiNodeRunner):
    name = "pdsh"

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, per_node_cmds):
        return [f"pdsh -S -w {host} {shlex.quote(cmd)}"
                for host, cmd in per_node_cmds]


class SSHRunner(MultiNodeRunner):
    name = "ssh"

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self, per_node_cmds):
        return [f"ssh -o StrictHostKeyChecking=no {host} {shlex.quote(cmd)}"
                for host, cmd in per_node_cmds]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun with per-host slot counts; env exported via -x (reference
    OpenMPIRunner)."""

    name = "openmpi"
    exports = ("MASTER_ADDR", "MASTER_PORT", "WORLD_SIZE", "PYTHONPATH",
               "JAX_PLATFORMS", "XLA_FLAGS")

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, per_node_cmds):
        hostlist = ",".join(f"{h}:{len(s)}" for h, s in self.world_info.items())
        exports = " ".join(f"-x {k}" for k in self.exports if k in os.environ)
        # one process per node; the per-node spawner fans out local ranks
        node_cmd = per_node_cmds[0][1]
        return [f"mpirun --allow-run-as-root -np {self.num_nodes} "
                f"-H {hostlist} {exports} bash -c {shlex.quote(node_cmd)}"]


class MPICHRunner(MultiNodeRunner):
    name = "mpich"

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, per_node_cmds):
        hostlist = ",".join(self.world_info)
        node_cmd = per_node_cmds[0][1]
        return [f"mpirun -np {self.num_nodes} -hosts {hostlist} "
                f"bash -c {shlex.quote(node_cmd)}"]


class SlurmRunner(MultiNodeRunner):
    """srun across the allocation (reference SlurmRunner): one task per
    node, nodelist from the hostfile/allocation."""

    name = "slurm"

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, per_node_cmds):
        nodelist = ",".join(self.world_info)
        node_cmd = per_node_cmds[0][1]
        return [f"srun --nodes={self.num_nodes} --ntasks={self.num_nodes} "
                f"--nodelist={nodelist} bash -c {shlex.quote(node_cmd)}"]


RUNNERS = {cls.name: cls for cls in
           (PDSHRunner, SSHRunner, OpenMPIRunner, MPICHRunner, SlurmRunner)}


def build_runner(name: str, args, world_info) -> MultiNodeRunner:
    if name not in RUNNERS:
        raise ValueError(f"unknown launcher {name!r}; known: {sorted(RUNNERS)}")
    return RUNNERS[name](args, world_info)
