"""The serving-program registry: trace the REAL frame loops on tiny
abstract shapes so Family A checks the programs production actually runs.

``build_serving_programs()`` constructs a tiny f32 engine (and, when the
process has >= 8 devices — the conftest/CLI force a virtual CPU mesh — a
tp=8 twin plus self-draft speculative variants) and returns one
``TracedProgram`` per serving entry point x shape bucket:

- ``frame_loop`` at width=chunk (prefill frames) and width=1 (decode),
- ``frame_loop_spec`` (speculative decode frames, gamma=2),
- ``mixed_loop`` / ``mixed_loop_spec`` (the compiled-generation path),
- ``decode_loop`` and the per-chunk ``run`` program.

Tracing never compiles or executes — ``jit.trace`` stops at the jaxpr — so
the whole registry costs seconds on CPU. Donation indices come from the
live ``Traced.donate_argnums``, which is also what keeps
``ast_checks.DISPATCH_DONATIONS`` honest (the test suite cross-checks the
two).

The tp programs are traced with the default EXACT collectives: the
T3-style ring lowering (``tp_overlap_collectives``) is replica-invariant
by ring algebra, not by local dataflow, so the GL003 taint pass cannot
prove it — that lowering stays covered by the dynamic parity suites and
``tp_debug_replica_check`` instead of a static false positive.
"""

import functools
from typing import List, Optional

from .jaxpr_checks import TracedProgram

_GAMMA = 2


def _tiny_engine(tp: int = 1, quantized: bool = False, overlap: bool = False,
                 payload: str = "int8", kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None):
    import jax
    from ..models import build_model
    from ..inference.v2.engine_v2 import (InferenceEngineV2,
                                          RaggedInferenceEngineConfig)
    model = build_model("tiny", num_heads=8)
    params = model.init(jax.random.PRNGKey(0))
    cfg = RaggedInferenceEngineConfig(
        kv_block_size=16, prefill_chunk_size=8, max_tokens_per_step=64,
        max_ragged_batch_size=4, frame_steps=2, dtype="float32", tp=tp,
        tp_quantized_collectives=quantized, tp_overlap_collectives=overlap,
        tp_collective_payload=payload, kv_dtype=kv_dtype,
        weight_dtype=weight_dtype)
    eng = InferenceEngineV2(model, cfg, params=params, max_seq_len=64)
    eng.attach_draft(model, params)    # self-draft: spec loops traceable
    return eng


def _slot_table(eng):
    import jax
    from ..inference.v2.ragged_manager import DeviceSlotTable
    return DeviceSlotTable(4, prompt_width=8, table_width=4,
                           rng=jax.random.PRNGKey(0), tp=eng.tp_ctx)


def _frame_args(eng, slots):
    kv = eng.kv
    return (eng.params, slots.prompts, slots.prompt_lens, slots.limits,
            slots.eos_ids, slots.temps, slots.tables, slots.cached,
            slots.produced, slots.last_tok, slots.done, slots.poison,
            slots.nonfinite, slots.stats, slots.rng, kv.k, kv.v)


def _spec_args(eng, slots):
    kv, dkv = eng.kv, eng.draft_kv
    return (eng.params, eng.draft_params, slots.prompts, slots.prompt_lens,
            slots.limits, slots.eos_ids, slots.temps, slots.tables,
            slots.cached, slots.produced, slots.last_tok, slots.penult,
            slots.done, slots.poison, slots.nonfinite, slots.stats,
            slots.rng, kv.k, kv.v, dkv.k, dkv.v)


def _mixed_args(eng):
    import jax
    import jax.numpy as jnp
    b, pmax = 2, 8
    prompts = jnp.zeros((b, pmax), jnp.int32)
    plens = jnp.full((b,), pmax, jnp.int32)
    limits = jnp.full((b,), 4, jnp.int32)
    tables = jnp.zeros((b, 4), jnp.int32)
    rng = jax.random.PRNGKey(0)
    return prompts, plens, limits, tables, rng, jnp.float32(0.0)


def _program(name, builder, args, statics) -> TracedProgram:
    """Wrap one jitted entry point. ``builder()`` must return a FRESH jit
    every call (fresh trace, no jit-cache hit) — check_retrace depends on
    it."""
    def trace():
        return builder().trace(*args, **statics)
    prog = TracedProgram(name=name, trace=trace, retrace=trace)
    try:
        import bisect
        import jax
        tr = prog.traced()
        # Traced.donate_argnums index the FLAT arg leaves (a param pytree
        # expands to one index per leaf); keep those for the aval-matching
        # check and ALSO map them back to user positional args for the
        # DISPATCH_DONATIONS cross-check in tests/test_static_analysis.py
        prog.donate_argnums = tuple(tr.donate_argnums)
        bounds, total = [], 0
        for a in args:
            total += len(jax.tree_util.tree_leaves(a))
            bounds.append(total)
        prog.donate_user_args = tuple(sorted(
            {bisect.bisect_right(bounds, i) for i in prog.donate_argnums}))
    except Exception:          # noqa: BLE001 — checks surface it as findings
        pass
    return prog


def _engine_programs(eng, tag: str) -> List[TracedProgram]:
    import jax.numpy as jnp
    runner, draft_runner = eng.runner, eng.draft_runner
    slots = _slot_table(eng)
    frame = functools.partial(_frame_args, eng, slots)
    spec = functools.partial(_spec_args, eng, slots)
    prompts, plens, limits, tables, rng, temp = _mixed_args(eng)
    kv, dkv = eng.kv, eng.draft_kv
    progs = [
        _program(f"frame_loop[w=8]{tag}", runner._build_frame_loop, frame(),
                 dict(width=8, steps=2, greedy=True)),
        _program(f"frame_loop[w=1]{tag}", runner._build_frame_loop, frame(),
                 dict(width=1, steps=2, greedy=True)),
        # nonfinite_policy="repair" compiles DISTINCT programs (the
        # pre-fault-carry rollback selects are static-gated) — a repair
        # engine runs the repair variant of EVERY frame program it
        # dispatches (wide prefill frames and the speculative loop
        # included), so each needs its own GL001-GL004 coverage
        _program(f"frame_loop[w=1,repair]{tag}", runner._build_frame_loop,
                 frame(), dict(width=1, steps=2, greedy=True, repair=True)),
        _program(f"frame_loop[w=8,repair]{tag}", runner._build_frame_loop,
                 frame(), dict(width=8, steps=2, greedy=True, repair=True)),
        _program(f"frame_loop_spec[w=1]{tag}",
                 lambda: runner._build_frame_loop_spec(draft_runner), spec(),
                 dict(width=1, steps=2, greedy=True, gamma=_GAMMA)),
        _program(f"frame_loop_spec[w=1,repair]{tag}",
                 lambda: runner._build_frame_loop_spec(draft_runner), spec(),
                 dict(width=1, steps=2, greedy=True, gamma=_GAMMA,
                      repair=True)),
        # a draft-carrying engine dispatches its WIDE (prefill) frames
        # through frame_loop_spec too — width=chunk is a distinct compiled
        # program (the draft ingests the same chunk), so it needs its own
        # coverage; the registry-completeness test pins this variant matrix
        _program(f"frame_loop_spec[w=8]{tag}",
                 lambda: runner._build_frame_loop_spec(draft_runner), spec(),
                 dict(width=8, steps=2, greedy=True, gamma=_GAMMA)),
        _program(f"frame_loop_spec[w=8,repair]{tag}",
                 lambda: runner._build_frame_loop_spec(draft_runner), spec(),
                 dict(width=8, steps=2, greedy=True, gamma=_GAMMA,
                      repair=True)),
        _program(f"mixed_loop{tag}", runner._build_mixed_loop,
                 (eng.params, prompts, plens, limits, kv.k, kv.v, tables,
                  rng, temp),
                 dict(chunk=8, wide_steps=1, narrow_steps=2, greedy=True)),
        _program(f"mixed_loop_spec{tag}",
                 lambda: runner._build_mixed_loop_spec(draft_runner),
                 (eng.params, eng.draft_params, prompts, plens, limits,
                  kv.k, kv.v, dkv.k, dkv.v, tables, rng, temp),
                 dict(chunk=8, wide_steps=1, narrow_steps=2, greedy=True,
                      gamma=_GAMMA)),
    ]
    if eng.tp_ctx is None:
        # host-step paths never compile under shard_map; trace them once
        last = jnp.zeros((2,), jnp.int32)
        lens = jnp.full((2,), 8, jnp.int32)
        progs.append(_program(
            f"decode_loop{tag}", runner._build_decode_loop,
            (eng.params, last, lens, tables, kv.k, kv.v, rng, temp),
            dict(steps=2, greedy=True)))
        ids = jnp.zeros((2, 8), jnp.int32)
        pos = jnp.zeros((2, 8), jnp.int32)
        valid = jnp.full((2,), 8, jnp.int32)
        progs.append(_program(
            f"run[chunk=8]{tag}", lambda: runner._build(8),
            (eng.params, ids, pos, tables, valid, kv.k, kv.v), {}))
        # KV memory-hierarchy page movers (kv_cache.py / kv_hierarchy.py):
        # the frame-BOUNDARY device programs behind copy-on-write block
        # copies and host-RAM swap restores — donation- and transfer-
        # checked exactly like the frame loops (they run between frames,
        # so a host-sync primitive inside one would still be a boundary
        # stall worth catching; identical program under tp via GSPMD)
        from ..inference.v2.kv_cache import BlockedKVCache
        bids = jnp.zeros((2,), jnp.int32)
        # pool row width comes from kv.lanes: head_dim for float pools,
        # head_dim + packed scale lanes for int8 pools — the movers ship
        # whatever representation the pool holds
        pages = jnp.zeros((kv.num_layers, kv.kv_heads, 2, kv.block_size,
                           kv.lanes), kv.k.dtype)
        progs.append(_program(
            f"copy_blocks{tag}", BlockedKVCache._build_copy_blocks,
            (kv.k, kv.v, bids, bids), {}))
        progs.append(_program(
            f"scatter_pages{tag}", BlockedKVCache._build_scatter_pages,
            (kv.k, kv.v, bids, pages, pages), {}))
        progs.append(_program(
            f"gather_pages{tag}", BlockedKVCache._build_gather_pages,
            (kv.k, kv.v, bids), {}))
    return progs


def build_serving_programs(include_tp: Optional[bool] = None
                           ) -> List[TracedProgram]:
    """Trace every serving entry point; ``include_tp=None`` auto-detects
    (>= 8 devices). Returns the registry the lint CLI and the repo
    regression test both walk.

    Role coverage (ISSUE 12): the disaggregated prefill/decode fleet
    introduces NO new compiled programs — a ``role="prefill"`` engine
    dispatches the already-registered wide ``frame_loop[w=8]`` (and spec)
    variants, a decode replica the width-1 ones, and every tier transfer
    (handoff publish/restore, prefix-record restore) goes through the
    registered ``gather_pages``/``scatter_pages``/``copy_blocks`` movers
    at frame boundaries. Handoff/classification/commit logic is host-side
    policy, so GL001–GL004 and the Family C cost ledger cover the
    disaggregated fleet through this same registry — the completeness
    test cross-checks that no serve() dispatch site exists outside it."""
    import jax
    progs = _engine_programs(_tiny_engine(tp=1), "")
    # the quantized serving stack (kv_dtype/weight_dtype int8) compiles
    # DISTINCT programs — int8 pools with packed scale lanes, dequant at
    # the attention read, quantize at append, int8 weight dequant in every
    # matmul — so each gets its own GL001-GL004 + Family C coverage; the
    # page movers re-trace over int8 pools (the swap tier moves the
    # quantized representation, which is the 2-4x tier-I/O claim)
    progs += _engine_programs(
        _tiny_engine(kv_dtype="int8", weight_dtype="int8"), "[quant]")
    if include_tp is None:
        include_tp = len(jax.devices()) >= 8
    if include_tp:
        progs += _engine_programs(_tiny_engine(tp=8), "[tp=8]")
    return progs


#: base entry points re-traced under each non-default collective lowering
#: for the Family C payload contracts (GL202): the frame/mixed loops issue
#: the per-layer psums + the logit gather, which is everything the
#: quantized/overlap flags touch. Repair twins are skipped — the repair
#: selects change no collective, so their payloads are the non-repair ones.
_COST_VARIANT_BASES = ("frame_loop[w=8]", "frame_loop[w=1]",
                       "frame_loop_spec[w=1]", "frame_loop_spec[w=8]",
                       "mixed_loop", "mixed_loop_spec")


def _variant_programs(eng, tag: str, variant: str) -> List[TracedProgram]:
    progs = [p for p in _engine_programs(eng, tag)
             if p.name.replace(tag, "") in _COST_VARIANT_BASES]
    for p in progs:
        p.variant = variant
        p.counterpart = p.name.replace(tag, "[tp=8]")
    return progs


def build_cost_programs(include_tp: Optional[bool] = None
                        ) -> List[TracedProgram]:
    """The Family C (graft-cost) registry: every serving program the
    GL001-GL004 registry traces — same engines, same shapes, so the two
    families describe the same compiled artifacts — PLUS tp=8 twins traced
    under the non-default collective lowerings:

    - ``variant="quantized"`` (``tp_quantized_collectives``): the EQuARX
      int8 programs GL202 payload-compares against their exact
      counterparts;
    - ``variant="overlap"`` (``tp_overlap_collectives``): the T3 ring
      programs whose total wire bytes must EQUAL the exact psum's
      (2(N-1) ppermute chunks x chunk bytes = the ring all-reduce cost).

    The variant twins get GL001/GL002 coverage from the cost gate but NOT
    GL003 (the ring is replica-invariant by ring algebra, which the taint
    pass cannot prove — same reason the main registry traces exact
    collectives only) and not GL004 (one trace each; the exact twins
    already pin retrace determinism of the shared entry points)."""
    import jax
    if include_tp is None:
        include_tp = len(jax.devices()) >= 8
    progs = build_serving_programs(include_tp=include_tp)
    if include_tp:
        progs += _variant_programs(_tiny_engine(tp=8, quantized=True),
                                   "[tp=8,quant]", "quantized")
        # fp8 (e4m3) wire variant: same one-byte payload contract as int8,
        # proven by the same GL202 comparison against the exact twins
        # (CostReport.int8_payload counts float8_* collective operands too)
        progs += _variant_programs(
            _tiny_engine(tp=8, quantized=True, payload="fp8"),
            "[tp=8,fp8]", "quantized")
        progs += _variant_programs(_tiny_engine(tp=8, overlap=True),
                                   "[tp=8,ring]", "overlap")
    return progs
