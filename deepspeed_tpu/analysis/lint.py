"""graft-lint CLI.

::

    python -m deepspeed_tpu.analysis.lint deepspeed_tpu/ \
        --baseline .graft-lint-baseline.json
    bin/dstpu_lint --format json deepspeed_tpu/inference/
    bin/dstpu_lint --cost-report            # per-program cost table
    bin/dstpu_lint --update-cost-baseline   # re-record .graft-cost-baseline

Runs Family B (AST) over the given paths and, unless ``--ast-only``,
Family A (jaxpr invariants over the traced serving programs) plus
Family C (graft-cost: the static cost model, rules GL201-GL204, gated
against the committed ``.graft-cost-baseline.json``); applies inline
suppressions, then the baseline; exits 0 when no NEW findings remain, 1
otherwise, 2 on an internal error. ``--write-baseline`` records the
current findings as accepted (repo policy: keep it empty — fix or
inline-suppress instead). ``--update-cost-baseline`` re-records the cost
baseline — the resulting diff belongs in the PR description.

The jaxpr family needs a CPU backend with >= 8 devices to trace the
tensor-parallel programs; the CLI forces the same virtual mesh the test
suite uses, so it must set the environment BEFORE jax first imports —
hence the lazy imports below.
"""

import argparse
import json
import os
import sys
from typing import Dict, List

from .ast_checks import check_donation_sites, check_module
from .findings import (RULES, Finding, apply_suppressions, filter_baseline,
                       load_baseline, sort_findings, write_baseline)

#: files whose dispatch sites must rebind donated carries (GL002 AST half)
_DONATION_FILES = ("engine_v2.py", "ragged_manager.py")


def _iter_py_files(target: str) -> List[str]:
    if os.path.isfile(target) and target.endswith(".py"):
        return [target]
    out = []
    if os.path.isdir(target):
        for root, dirs, files in os.walk(target):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(set(out))


def _anchor_for(target: str) -> str:
    """The directory finding paths are made relative to: the enclosing
    REPO root (first parent holding .git/setup.py/pyproject.toml), so the
    same file gets the same path — and the same baseline fingerprint —
    whether the whole package or one changed file was scanned, from any
    CWD. Outside any repo, fall back to the target's parent."""
    d = target if os.path.isdir(target) else os.path.dirname(target)
    probe = d
    while True:
        if any(os.path.exists(os.path.join(probe, m))
               for m in (".git", "setup.py", "pyproject.toml")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            # no repo marker anywhere above: anchor at the target's own
            # directory, so a file scan and a scan of its containing dir
            # still agree (deeper-nested dir scans cannot be reconciled
            # without a marker — add one for stable baselines)
            return d
        probe = parent


def run_ast_family(paths: List[str]) -> (List[Finding], Dict[str, str]):
    """Finding paths are made relative to the enclosing repo root (see
    ``_anchor_for``) — NOT the process CWD — so baseline fingerprints
    match across invocation directories AND scan granularities."""
    findings: List[Finding] = []
    sources: Dict[str, str] = {}
    seen = set()
    for target in paths:
        target = os.path.abspath(target)
        anchor = _anchor_for(target)
        for path in _iter_py_files(target):
            if path in seen:
                continue
            seen.add(path)
            rel = os.path.relpath(path, anchor)
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
            except OSError as e:
                print(f"graft-lint: cannot read {rel}: {e}", file=sys.stderr)
                continue
            sources[rel] = src
            findings.extend(check_module(rel, src))
            if os.path.basename(path) in _DONATION_FILES:
                findings.extend(check_donation_sites(rel, src))
    return findings, sources


def run_jaxpr_family(include_tp=None, programs=None) -> List[Finding]:
    """Trace the serving registry and run the jaxpr checks: the full
    GL001-GL004 set on exact-collectives programs, GL001/GL002 on the cost
    registry's quantized/ring variant twins (see
    ``jaxpr_checks.check_variant_program``). Imports jax lazily — callers
    must have set the platform env first."""
    import logging
    # silence engine-construction INFO spam for the duration of the trace
    # ONLY — leaving the level at ERROR would permanently mute the
    # serving stack's rate-limited overload/fault warnings for the rest
    # of the process (a test importing this gate then loses every
    # logger.warning assertion after it)
    ds_logger = logging.getLogger("DeepSpeedTPU")
    prev_level = ds_logger.level
    ds_logger.setLevel(logging.ERROR)
    try:
        from .jaxpr_checks import check_program, check_variant_program
        if programs is None:
            from .programs import build_serving_programs
            programs = build_serving_programs(include_tp=include_tp)
        findings: List[Finding] = []
        for prog in programs:
            if prog.variant == "exact":
                findings.extend(check_program(prog))
            else:
                findings.extend(check_variant_program(prog))
    finally:
        ds_logger.setLevel(prev_level)
    return findings


def run_cost_family(programs, baseline_path=None, include_tp=True):
    """Family C over an already-traced registry: measure every program and
    run GL201 (when a baseline is available) + GL202/GL203/GL204. Returns
    (findings, reports)."""
    from .cost_model import load_cost_baseline, run_cost_checks
    baseline = None
    if baseline_path is not None:
        baseline = load_cost_baseline(baseline_path)
    return run_cost_checks(programs, baseline=baseline,
                           include_tp=include_tp)


def _force_cpu_mesh() -> None:
    """Same dance as tests/conftest.py: the jaxpr family traces shard_map
    programs over a virtual 8-device CPU mesh; everything must be pinned
    before jax initializes a backend."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=8"
    os.environ.setdefault("JAX_ENABLE_X64", "0")
    import jax
    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis.lint",
        description="graft-lint: static analysis for the compiled serving "
                    "stack (jaxpr invariants + AST retrace hazards)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to AST-lint (default: deepspeed_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", metavar="FILE",
                    help="accepted-findings file; only NEW findings fail")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings into --baseline and exit 0")
    ap.add_argument("--ast-only", action="store_true",
                    help="skip the jaxpr AND cost families (no tracing/"
                         "engine builds; via bin/dstpu_lint this also skips "
                         "the framework import entirely)")
    ap.add_argument("--no-tp", action="store_true",
                    help="skip the tensor-parallel (shard_map) programs")
    ap.add_argument("--no-cost", action="store_true",
                    help="skip Family C (the graft-cost model, GL201-GL204)")
    ap.add_argument("--cost-baseline", metavar="FILE",
                    help="cost-baseline file for GL201 (default: "
                         ".graft-cost-baseline.json at the repo root of the "
                         "first scanned path; GL201 is skipped if the "
                         "default is absent, exit 2 if an explicit one is)")
    ap.add_argument("--update-cost-baseline", action="store_true",
                    help="re-record every program's cost metrics into the "
                         "cost baseline and exit 0 (the diff belongs in the "
                         "PR description)")
    ap.add_argument("--cost-report", action="store_true",
                    help="print the per-program cost table (markdown, or "
                         "structured with --format json) and exit 0")
    ap.add_argument("--rules", metavar="GL001,GL101,...",
                    help="restrict to these rule ids")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (name, sev, what, dyn) in sorted(RULES.items()):
            print(f"{rid}  {name:<22} {sev:<8} {what}")
        return 0

    paths = args.paths or [os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))]   # deepspeed_tpu/
    for p in paths:
        if not os.path.exists(p):
            # a typo'd target must not report "clean" with 0 files scanned
            print(f"graft-lint: no such file or directory: {p}",
                  file=sys.stderr)
            return 2
    if args.ast_only and (args.cost_report or args.update_cost_baseline):
        ap.error("--cost-report/--update-cost-baseline trace the serving "
                 "programs and cannot combine with --ast-only")
    if args.update_cost_baseline and (args.no_tp or args.no_cost):
        # a partial registry would overwrite the committed baseline
        # wholesale, silently dropping every tp/quantized/ring entry
        ap.error("--update-cost-baseline records the FULL registry and "
                 "cannot combine with --no-tp/--no-cost")
    findings, sources = run_ast_family(paths)
    if not args.ast_only:
        # trace-time only (restored below): in-process callers — the repo
        # gate tests import main() — must get their warning level back
        import logging
        _ds_logger = logging.getLogger("DeepSpeedTPU")
        _prev_level = _ds_logger.level
        try:
            _force_cpu_mesh()
            import jax
            _ds_logger.setLevel(logging.ERROR)
            include_tp = (False if args.no_tp
                          else len(jax.devices()) >= 8)
            run_cost = not args.no_cost
            if run_cost:
                from .programs import build_cost_programs
                programs = build_cost_programs(include_tp=include_tp)
            else:
                from .programs import build_serving_programs
                programs = build_serving_programs(include_tp=include_tp)
            cost_base = args.cost_baseline or os.path.join(
                _anchor_for(os.path.abspath(paths[0])),
                ".graft-cost-baseline.json")
            if args.update_cost_baseline:
                from .cost_model import run_cost_checks, write_cost_baseline
                _, reports = run_cost_checks(programs, baseline=None)
                write_cost_baseline(cost_base, reports)
                print(f"graft-lint: recorded cost metrics for "
                      f"{len(reports)} program(s) to {cost_base}",
                      file=sys.stderr)
                return 0
            if args.cost_report:
                from .cost_model import render_cost_table, run_cost_checks
                _, reports = run_cost_checks(programs, baseline=None)
                if args.format == "json":
                    print(json.dumps(
                        {"cost_report": [r.as_json() for r in sorted(
                            reports, key=lambda r: r.name)]}, indent=2))
                else:
                    print(render_cost_table(reports))
                return 0
            findings.extend(run_jaxpr_family(programs=programs))
            if run_cost:
                if not os.path.exists(cost_base):
                    if args.cost_baseline:
                        print(f"graft-lint: cannot read cost baseline "
                              f"{cost_base}: no such file", file=sys.stderr)
                        return 2
                    print(f"graft-lint: no cost baseline at {cost_base} — "
                          "GL201 skipped (record one with "
                          "--update-cost-baseline)", file=sys.stderr)
                    cost_base = None
                cost_findings, _ = run_cost_family(
                    programs, baseline_path=cost_base,
                    include_tp=include_tp)
                findings.extend(cost_findings)
        except Exception as e:            # noqa: BLE001
            print(f"graft-lint: jaxpr/cost families failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        finally:
            _ds_logger.setLevel(_prev_level)

    findings = apply_suppressions(findings, sources)
    if args.rules:
        keep = {r.strip() for r in args.rules.split(",")}
        findings = [f for f in findings if f.rule in keep]
    findings = sort_findings(findings)

    if args.write_baseline:
        if not args.baseline:
            ap.error("--write-baseline requires --baseline FILE")
        write_baseline(args.baseline, findings)
        print(f"graft-lint: wrote {len(findings)} fingerprint(s) to "
              f"{args.baseline}", file=sys.stderr)
        return 0

    new = findings
    if args.baseline:
        # a missing or broken baseline must not silently degrade to a
        # no-baseline run (every baselined finding would report as NEW) —
        # and must not masquerade as "findings" either: exit 2, not 1
        try:
            new = filter_baseline(findings, load_baseline(args.baseline))
        except (ValueError, OSError) as e:
            print(f"graft-lint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    if args.format == "json":
        print(json.dumps({
            "findings": [f.as_json() for f in new],
            "baselined": len(findings) - len(new),
            "counts": _counts(new)}, indent=2))
    else:
        for f in new:
            print(f.render())
        base_note = (f" ({len(findings) - len(new)} baselined)"
                     if len(findings) != len(new) else "")
        if new:
            counts = ", ".join(f"{k}={v}" for k, v in _counts(new).items())
            print(f"graft-lint: {len(new)} finding(s){base_note}: {counts}")
        else:
            print(f"graft-lint: clean{base_note}")
    return 1 if new else 0


def _counts(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return dict(sorted(out.items()))


if __name__ == "__main__":
    sys.exit(main())
