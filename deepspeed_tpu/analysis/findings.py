"""Finding/rule model, inline suppressions, and the committed baseline.

A finding is one violation of one rule at one location. Locations are
either source positions (Family B, and the AST half of GL002) or traced
PROGRAMS (Family A — a jaxpr has no line number, so the program name is the
location and the fingerprint context).

Fingerprints are content-addressed, not line-addressed: ``rule | path |
context | message`` — moving code around a file does not churn the
baseline, changing what the code *does* does. The baseline file
(``.graft-lint-baseline.json``) holds fingerprints of findings that are
accepted as pre-existing; the CLI exits non-zero only on findings NOT in
it. Per repo policy (ISSUE 7), real findings are fixed or inline-suppressed
with a justification — the baseline exists for third-party sweeps and
incremental adoption, and the committed one stays empty.

Inline suppression::

    x = float(steps)   # graft-lint: disable=GL104 -- steps is trace-static

applies to the physical line it sits on; a comment-only line suppresses the
next CODE line — further comment/blank lines in between (a multi-line
justification) are skipped over.
"""

import dataclasses
import hashlib
import json
import re
from typing import Dict, Iterable, List, Set

ERROR = "error"
WARNING = "warning"

#: rule id -> (short name, severity, what it catches, dynamic complement)
RULES: Dict[str, tuple] = {
    # ---- Family A: jaxpr-level invariant checks ----
    "GL000": ("trace-failure", ERROR,
              "serving program failed to trace for a reason no jaxpr rule "
              "classifies — GL001-GL004 could not run, so a 'clean' result "
              "would be vacuous for it",
              "the serving suites themselves"),
    "GL001": ("transfer-guard", ERROR,
              "host-sync primitive (callback/debug print/host coercion) "
              "reachable inside a compiled serving program",
              "tests/*: frame_transfer_guard fixture "
              "(jax.transfer_guard_device_to_host around dispatch_frame)"),
    "GL002": ("donation-safety", ERROR,
              "donated buffer with no matching output aval, or a dispatch "
              "site that does not rebind every donated carry from the "
              "call's results",
              "donated-buffer errors at runtime; token-parity suites"),
    "GL003": ("collective-structure", ERROR,
              "collective naming an axis not manual on the enclosing "
              "shard_map mesh, a non-permutation ppermute, or a "
              "declared-replicated output that is shard-varying",
              "tp_debug_replica_check=True per-boundary all-shard assert; "
              "tests/test_serving_tp.py parity suites"),
    "GL004": ("retrace-budget", ERROR,
              "serving entry point whose jaxpr differs across two traces "
              "of identical (bucket-compatible) shapes — a retrace per "
              "call in production",
              "compile_count_total() budgets in the serving tests"),
    # ---- Family C: jaxpr cost model (graft-cost; cost_model.py) ----
    "GL201": ("cost-regression", ERROR,
              "a per-program cost metric (matmul FLOPs, HBM bytes, "
              "collective payload bytes, boundary D2H bytes) drifted "
              "beyond tolerance vs the committed .graft-cost-baseline.json "
              "— unexplained growth fails; explain it and re-record with "
              "--update-cost-baseline",
              "serving_bench.py trend rows (SERVING_r*.json)"),
    "GL202": ("collective-lowering-contract", ERROR,
              "a non-default collective lowering breaks its payload "
              "contract: the tp_quantized_collectives program's int8 wire "
              "bytes exceed 0.5x the exact program's total (+ scales), or "
              "a tp_overlap_collectives ring program's total wire bytes "
              "differ from the exact psum's (2(N-1) chunks x chunk size)",
              "tests/test_serving_tp.py parity-at-tolerance contracts"),
    "GL203": ("boundary-transfer-budget", ERROR,
              "a frame program's host-read outputs exceed the boundary "
              "D2H budget: anything beyond the (steps, B) emission stream "
              "plus O(batch) per-row lanes scales a per-frame transfer "
              "with sequence length / vocab / pool size",
              "frame_transfer_guard fixture (existence complement: zero "
              "IN-frame D2H; this rule bounds the boundary's SIZE)"),
    "GL204": ("redundant-collective", ERROR,
              "the same operand reduced twice over the same mesh axis, a "
              "collective applied to an already-reduced (replica-"
              "invariant) value, or an all-gather whose result is "
              "immediately summed away — N x the wire bytes for a value "
              "one collective computes",
              "none (pure waste: numerically invisible)"),
    # ---- Family B: AST lint for retrace hazards ----
    "GL101": ("tracer-branch", ERROR,
              "Python `if`/`while`/`assert` on a traced value inside a "
              "jitted function or scan body (ConcretizationTypeError, or "
              "a silent retrace per distinct value)",
              "recompile-count assertions in tests/test_frame_serving.py"),
    "GL102": ("unhashable-static", ERROR,
              "list/dict/set literal passed for a static jit argument "
              "(unhashable cache key -> TypeError or a retrace per call)",
              "compile_count() introspection in the serving tests"),
    "GL103": ("dtype-drift", WARNING,
              "float64-producing dtype in jitted code (dtype=float/"
              "np.float64, np.float64()/astype(float)) — silently "
              "downcast under x64-disabled, doubles traffic otherwise",
              "parity-at-tolerance suites (tests/test_serving_tp.py)"),
    "GL104": ("host-coercion", ERROR,
              "float()/int()/bool()/.item()/.tolist()/np.* array "
              "constructor on a value inside jitted code — a device sync "
              "(or constant-folded garbage) in the compiled path",
              "frame_transfer_guard fixture (in-frame D2H disallow)"),
    "GL105": ("print-in-jit", WARNING,
              "print() inside jitted code — runs once at trace time, "
              "not per step (use jax.debug.print, which GL001 then "
              "budgets)",
              "none (trace-time only)"),
}

_SEV_ORDER = {ERROR: 0, WARNING: 1}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # file relative to the scanned target's parent dir
    #                     (CWD-independent), or "<jaxpr>" for traced programs
    line: int           # 1-based; 0 = program-level (no source position)
    message: str
    context: str = ""   # program name / symbol — stable fingerprint salt

    @property
    def severity(self) -> str:
        return RULES[self.rule][1]

    @property
    def fingerprint(self) -> str:
        raw = "|".join((self.rule, self.path, self.context, self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        ctx = f" [{self.context}]" if self.context else ""
        return f"{loc}: {self.rule} ({self.severity}){ctx}: {self.message}"

    def as_json(self) -> Dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line,
                "context": self.context, "message": self.message,
                "fingerprint": self.fingerprint}


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (_SEV_ORDER[f.severity], f.path,
                                           f.line, f.rule, f.message))


# ---------------------------------------------------------------------------
# inline suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*graft-lint:\s*disable=([A-Z0-9,\s]+?)"
                          r"(?:\s--\s.*)?$")


def suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of rule ids suppressed there.

    A pragma on a code line covers that line; a pragma on a comment-only
    line covers the line itself AND the next CODE line (the flake8
    ``noqa``-above idiom) — intervening comment/blank lines, e.g. a
    justification spilling onto a second comment line, are skipped."""
    out: Dict[int, Set[str]] = {}
    lines = source.splitlines()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if text.lstrip().startswith("#"):
            j = i + 1
            while j <= len(lines) and (not lines[j - 1].strip()
                                       or lines[j - 1].lstrip()
                                       .startswith("#")):
                out.setdefault(j, set()).update(rules)
                j += 1
            if j <= len(lines):
                out.setdefault(j, set()).update(rules)
    return out


def apply_suppressions(findings: List[Finding],
                       sources: Dict[str, str]) -> List[Finding]:
    """Drop findings whose line carries a matching pragma. Program-level
    findings (line 0) have no source line and cannot be pragma-suppressed —
    fix them or baseline them."""
    per_file: Dict[str, Dict[int, Set[str]]] = {}
    kept = []
    for f in findings:
        if f.line and f.path in sources:
            if f.path not in per_file:
                per_file[f.path] = suppressed_lines(sources[f.path])
            if f.rule in per_file[f.path].get(f.line, ()):
                continue
        kept.append(f)
    return kept


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str) -> Set[str]:
    with open(path) as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unrecognized baseline version "
                         f"{data.get('version')!r}")
    return set(data.get("fingerprints", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    fps = sorted({f.fingerprint for f in findings})
    with open(path, "w") as fh:
        json.dump({"version": BASELINE_VERSION, "fingerprints": fps},
                  fh, indent=2)
        fh.write("\n")


def filter_baseline(findings: List[Finding],
                    baseline: Set[str]) -> List[Finding]:
    return [f for f in findings if f.fingerprint not in baseline]
