"""Family B: AST lint over ``deepspeed_tpu/`` for retrace hazards.

The jaxpr family (``jaxpr_checks``) sees everything a trace reaches but
only for the programs it traces; this family is the broad, syntactic
complement — it walks every ``.py`` file and flags hazard *patterns* inside
**jitted regions**:

- a function def decorated with ``jax.jit`` / ``functools.partial(jax.jit,
  ...)`` (or wrapped at an assignment ``f = jax.jit(g, ...)``), and
- a function passed as the body/branch of ``lax.scan`` / ``lax.while_loop``
  / ``lax.cond`` / ``lax.fori_loop`` anywhere (scan bodies are traced even
  when the def site is a plain module function).

Within a region the checker tracks which local names are (conservatively)
traced: the region's own non-static parameters seed the set, and any name
assigned from an expression that mentions a tracked name or calls into
``jnp``/``jax.lax``/``jax.nn``/``jax.random`` joins it. Closure variables
are deliberately NOT tracked — branching on ``self.tp``/``greedy``-style
trace-constants is the codebase's bread and butter and must not be flagged.
That makes the checker precise rather than complete: it catches the
retrace/ConcretizationTypeError hazards that enter through the traced
arguments, which is where every real incident has come from.
"""

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding

_CONTROL_FLOW_FNS = {"scan", "while_loop", "cond", "fori_loop", "switch",
                     "associative_scan"}
_TRACED_MODULES = {"jnp", "lax"}            # jnp.x(...), lax.x(...)
_NP_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "array", "asarray",
                    "arange", "linspace", "concatenate", "stack", "where"}
_HOST_COERCIONS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}


def _dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for an Attribute/Name chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _jit_static_info(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    """static_argnames/static_argnums from a jax.jit(...) /
    functools.partial(jax.jit, ...) call's keywords (literal values only —
    computed static specs are themselves a retrace smell, but not ours to
    prove here)."""
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    nums.add(el.value)
    return names, nums


@dataclasses.dataclass
class _Region:
    """One jitted region: a function def whose parameters are traced."""
    node: ast.AST                      # FunctionDef / Lambda
    kind: str                          # "jit" | "scan-body" | ...
    static_names: Set[str]
    static_nums: Set[int]

    def param_roots(self) -> Set[str]:
        args = self.node.args
        ordered = [a.arg for a in args.posonlyargs + args.args]
        roots = set()
        for i, name in enumerate(ordered):
            if name in ("self", "cls"):
                continue
            if name in self.static_names or i in self.static_nums:
                continue
            roots.add(name)
        roots.update(a.arg for a in args.kwonlyargs
                     if a.arg not in self.static_names)
        return roots


def _find_regions(tree: ast.AST) -> List[_Region]:
    """Jitted regions in one module (see module docstring)."""
    regions: List[_Region] = []
    defs: Dict[str, ast.AST] = {}
    lax_fns: Set[str] = set()      # `from jax.lax import scan as s` names
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
        elif isinstance(node, ast.ImportFrom) and node.module == "jax.lax":
            lax_fns.update(a.asname or a.name for a in node.names
                           if a.name in _CONTROL_FLOW_FNS)

    for node in ast.walk(tree):
        # decorated defs
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jax_jit(dec):
                    regions.append(_Region(node, "jit", set(), set()))
                elif isinstance(dec, ast.Call):
                    target = dec.func
                    if _is_jax_jit(target):
                        names, nums = _jit_static_info(dec)
                        regions.append(_Region(node, "jit", names, nums))
                    elif _dotted(target) in ("functools.partial", "partial") \
                            and dec.args and _is_jax_jit(dec.args[0]):
                        names, nums = _jit_static_info(dec)
                        regions.append(_Region(node, "jit", names, nums))
        if not isinstance(node, ast.Call):
            continue
        fn = _dotted(node.func)
        # f = jax.jit(g, static_argnames=...)
        if fn in ("jax.jit", "jit") and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Name) and inner.id in defs:
                names, nums = _jit_static_info(node)
                regions.append(_Region(defs[inner.id], "jit", names, nums))
            elif isinstance(inner, ast.Lambda):
                regions.append(_Region(inner, "jit", *_jit_static_info(node)))
        # lax.scan(body, ...), lax.cond(p, t, f), lax.while_loop(c, b, ...)
        elif fn and fn.rsplit(".", 1)[-1] in _CONTROL_FLOW_FNS:
            if "." in fn:
                if fn.rsplit(".", 2)[-2] != "lax":
                    continue
            elif fn not in lax_fns:
                # a bare `scan(...)`/`switch(...)` counts only when the
                # name was imported from jax.lax — a host-side helper
                # that happens to share the name must not turn its
                # callback args into "jitted regions"
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    regions.append(_Region(defs[arg.id], "scan-body",
                                           set(), set()))
                elif isinstance(arg, ast.Lambda):
                    regions.append(_Region(arg, "scan-body", set(), set()))
    # dedupe by node identity (a def can be both decorated and scanned)
    seen: Set[int] = set()
    out = []
    for r in regions:
        if id(r.node) not in seen:
            seen.add(id(r.node))
            out.append(r)
    return out


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _calls_traced_module(node: ast.AST) -> bool:
    for call in ast.walk(node):
        if isinstance(call, ast.Call):
            fn = _dotted(call.func)
            head = fn.split(".", 1)[0]
            if head in _TRACED_MODULES or fn.startswith("jax."):
                return True
    return False


def _tracked_names(region: _Region) -> Set[str]:
    """Fixpoint of 'this local name holds a traced value'."""
    tracked = region.param_roots()
    body = region.node.body if not isinstance(region.node, ast.Lambda) else []
    stmts = [s for node in body for s in ast.walk(node)]
    for _ in range(4):   # shallow chains; 4 passes covers the codebase
        grew = False
        for st in stmts:
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = st.value
                if value is None:
                    continue
                rhs_traced = bool(_names_in(value) & tracked) \
                    or _calls_traced_module(value)
                if not rhs_traced:
                    continue
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tracked:
                            tracked.add(n.id)
                            grew = True
        if not grew:
            break
    return tracked


def _own_statements(region: _Region, all_regions: List[_Region]):
    """Every node of this region EXCLUDING nested jitted regions (they are
    checked with their own root sets). Lambda bodies are walked too — a
    `lambda c, x: (c + float(x), c)` scan body must not escape just for
    being an expression."""
    nested = {id(r.node) for r in all_regions if r.node is not region.node}
    out = []
    stack = ([region.node.body] if isinstance(region.node, ast.Lambda)
             else list(region.node.body))
    while stack:
        node = stack.pop()
        if id(node) in nested:
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def check_module(path: str, source: str) -> List[Finding]:
    """All Family B findings for one file (suppressions NOT yet applied)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("GL101", path, e.lineno or 0,
                        f"file does not parse: {e.msg}")]
    findings: List[Finding] = []
    regions = _find_regions(tree)
    static_name_pool: Set[str] = set()
    for r in regions:
        static_name_pool |= r.static_names

    for region in regions:
        name = getattr(region.node, "name", "<lambda>")
        tracked = _tracked_names(region)
        nodes = _own_statements(region, regions)
        for node in nodes:
            findings.extend(_check_node(path, name, node, tracked,
                                        region.static_names))

    # GL102 — unhashable literals bound to known static argument names,
    # but ONLY at calls that plausibly reach a jit: the jitted defs
    # themselves or the runner's dispatch-wrapper methods. A host helper
    # that merely shares a kwarg name ('width=', 'steps=') must not trip
    # an error-severity finding.
    jit_callees = ({getattr(r.node, "name", None) for r in regions}
                   | set(DISPATCH_DONATIONS)) - {None}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func).rsplit(".", 1)[-1]
        if callee not in jit_callees:
            continue
        for kw in node.keywords:
            if kw.arg in static_name_pool and isinstance(
                    kw.value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                               ast.DictComp, ast.SetComp)):
                findings.append(Finding(
                    "GL102", path, kw.value.lineno,
                    f"static jit argument '{kw.arg}' receives an "
                    "unhashable literal — the jit cache key cannot hold "
                    "it (TypeError at dispatch, or a retrace per call "
                    "if coerced)", context=_dotted(node.func)))
    return findings


def _is_identity_test(expr: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` / ``isinstance(x, T)`` inspect the
    Python OBJECT, not the traced value — always trace-safe."""
    if isinstance(expr, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
        return True
    if isinstance(expr, ast.Call) and _dotted(expr.func) in (
            "isinstance", "hasattr", "callable"):
        return True
    if isinstance(expr, ast.BoolOp):
        return all(_is_identity_test(v) for v in expr.values)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
        return _is_identity_test(expr.operand)
    return False


def _check_node(path: str, region_name: str, node: ast.AST,
                tracked: Set[str], static_names: Set[str]) -> List[Finding]:
    out: List[Finding] = []

    def traced_expr(expr: ast.AST) -> bool:
        if _is_identity_test(expr):
            return False
        return bool((_names_in(expr) - static_names) & tracked) \
            or _calls_traced_module(expr)

    # GL101 — Python control flow on traced values
    if isinstance(node, (ast.If, ast.While)):
        if traced_expr(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append(Finding(
                "GL101", path, node.lineno,
                f"Python `{kind}` on a traced value — inside a jitted "
                "region this is a ConcretizationTypeError (or a retrace "
                "per distinct value if the operand is ever made static); "
                "use lax.cond/jnp.where", context=region_name))
    elif isinstance(node, ast.Assert) and traced_expr(node.test):
        out.append(Finding(
            "GL101", path, node.lineno,
            "Python `assert` on a traced value — dead under jit (traced "
            "once, never re-evaluated); use checkify or an in-graph "
            "latch like the serving finite-check", context=region_name))

    if not isinstance(node, ast.Call):
        return out
    fn = _dotted(node.func)

    def args_traced() -> bool:
        return any(bool((_names_in(a) - static_names) & tracked)
                   for a in node.args)

    # GL104 — host coercions
    if fn in _HOST_COERCIONS and node.args and not isinstance(
            node.args[0], ast.Constant) and args_traced():
        out.append(Finding(
            "GL104", path, node.lineno,
            f"`{fn}()` on a traced value forces a host sync (or raises "
            "under transfer guard) inside the compiled path",
            context=region_name))
    elif isinstance(node.func, ast.Attribute) \
            and node.func.attr in _HOST_METHODS:
        if traced_expr(node.func.value):
            out.append(Finding(
                "GL104", path, node.lineno,
                f"`.{node.func.attr}()` on a traced value is a "
                "device->host transfer inside the compiled path",
                context=region_name))
    elif fn.startswith("np.") or fn.startswith("numpy."):
        tail = fn.split(".", 1)[1]
        if tail in _NP_CONSTRUCTORS:
            out.append(Finding(
                "GL104", path, node.lineno,
                f"`{fn}()` inside a jitted region builds a HOST array — "
                "on a traced operand it device-syncs; on constants it "
                "bakes f64 trace-time values (use jnp)",
                context=region_name))
        elif tail in ("float64", "float32", "int64") and args_traced():
            out.append(Finding(
                "GL104", path, node.lineno,
                f"`{fn}()` coerces a traced value through numpy "
                "(host sync + strong f64 promotion)", context=region_name))

    # GL103 — float64 dtype drift
    for kw in node.keywords:
        if kw.arg == "dtype" and _dotted(kw.value) in (
                "float", "np.float64", "numpy.float64", "jnp.float64"):
            out.append(Finding(
                "GL103", path, node.lineno,
                f"dtype={_dotted(kw.value)} in a jitted region: silently "
                "downcast to f32 with x64 disabled, doubled "
                "bandwidth/promotion drift otherwise — name a concrete "
                "32-bit (or narrower) dtype", context=region_name))
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args and _dotted(node.args[0]) in (
                "float", "np.float64", "numpy.float64", "jnp.float64"):
        out.append(Finding(
            "GL103", path, node.lineno,
            "`.astype(float)` is float64 — promotion drift in a jitted "
            "region (name a concrete dtype)", context=region_name))

    # GL105 — print at trace time
    if fn == "print":
        out.append(Finding(
            "GL105", path, node.lineno,
            "print() in a jitted region runs ONCE at trace time — use "
            "jax.debug.print if per-step output is intended (and budget "
            "it: GL001 counts the resulting callback)",
            context=region_name))
    return out


# ---------------------------------------------------------------------------
# GL002 (AST half): donated-carry rebinding at dispatch sites
# ---------------------------------------------------------------------------

#: callee attr name -> (positions of donated args AT THE CALL SITE,
#: counting positional args only). Derived from the runner's jit
#: donate_argnums shifted by any leading non-jit params of the wrapper
#: (frame_loop_spec/mixed_loop_spec take draft_runner first, run takes
#: chunk first). tests/test_static_analysis.py cross-checks these against
#: the live ``Traced.donate_argnums`` so the table cannot rot silently.
DISPATCH_DONATIONS: Dict[str, Tuple[int, ...]] = {
    "frame_loop": tuple(range(7, 17)),
    "frame_loop_spec": tuple(range(9, 22)),
    "mixed_loop": (4, 5),
    "mixed_loop_spec": (6, 7, 8, 9),
    "decode_loop": (4, 5),
    "run": (6, 7),
    # KV memory-hierarchy page movers (kv_cache.py): both donate the two
    # pools they rewrite in place (COW copies / swap-in restores)
    "copy_blocks": (0, 1),
    "scatter_pages": (0, 1),
}


def check_donation_sites(path: str, source: str,
                         registry: Optional[Dict[str, Tuple[int, ...]]] = None
                         ) -> List[Finding]:
    """Every call to a donating runner entry point must rebind each donated
    argument from the call's result tuple in the SAME statement — the
    pattern ``(toks, emit, self.cached, ...) = runner.frame_loop(...,
    self.cached, ...)``. A dispatch that keeps using the old reference
    reads a donated (dead) buffer."""
    registry = DISPATCH_DONATIONS if registry is None else registry
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []
    findings: List[Finding] = []
    # scopes to scan: each function def, plus the module top level. A
    # donated argument counts as rebound if ANY assignment in the same
    # scope targets the same expression — covering both the one-statement
    # tuple-unpack idiom and the assign-then-rebind refactor of it.
    scopes = [n for n in ast.walk(tree)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    scopes.append(tree)

    def scope_walk(scope):
        """Nodes of this scope only — nested defs are their own scope."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(n))

    for scope in scopes:
        rebound: List[str] = []
        calls = []
        for node in scope_walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    elts = t.elts if isinstance(t, ast.Tuple) else [t]
                    rebound.extend(ast.unparse(e) for e in elts)
            if not isinstance(node, (ast.Assign, ast.Expr)):
                continue
            value = node.value
            if isinstance(value, ast.Call) \
                    and isinstance(value.func, ast.Attribute) \
                    and value.func.attr in registry:
                calls.append(value)
        for value in calls:
            name = value.func.attr
            for pos in registry[name]:
                if pos >= len(value.args):
                    continue   # fewer positional args (kwargs form) — skip
                arg = value.args[pos]
                if isinstance(arg, ast.Constant):
                    continue
                if ast.unparse(arg) not in rebound:
                    findings.append(Finding(
                        "GL002", path, value.lineno,
                        f"call to {name}() donates argument "
                        f"{ast.unparse(arg)!r} (position {pos}) but no "
                        "assignment in the enclosing scope rebinds it "
                        "from the results — the caller keeps a reference "
                        "to a dead buffer", context=name))
    return findings
