"""Family C: graft-cost — a static jaxpr cost model for the serving stack.

The CPU virtual mesh can prove token-parity but not speed: SERVING_r08's
per-chip ratio measures sharding *overhead*, so every performance claim the
serving stack makes (T3 ring overlap, EQuARX int8 exchanges, O(batch)
boundaries) was enforced only by tolerance tests. This pass makes the
traced serving programs a *quantitative* contract: it interprets each
program's ClosedJaxpr into a :class:`CostReport` — matmul FLOPs, HBM bytes,
per-axis collective wire bytes, frame-boundary D2H bytes — and gates four
rules on the result:

- **GL201 cost-regression** — every metric of every program is compared
  against the committed ``.graft-cost-baseline.json``; drift beyond
  tolerance (either direction — growth is a regression, shrink is a stale
  baseline) fails. Updating the baseline is an explicit
  ``--update-cost-baseline``, and the diff belongs in the PR description.
- **GL202 collective-lowering contract** — the ``tp_quantized_collectives``
  program's int8 wire bytes must be <= 0.5x the exact program's total
  collective payload (+ f32 scales), and the ``tp_overlap_collectives``
  ring program's total wire bytes must EQUAL the exact program's
  (2(N-1) ppermute chunks x chunk bytes == the psum's ring cost) — the
  arXiv 2506.17615 / 2401.16677 claims proven statically, per program.
- **GL203 boundary-transfer budget** — the bytes the host reads back per
  frame (``HOST_READ_OUTPUTS``) must fit the emission stream plus
  O(batch) per-row lanes: nothing a frame returns to the host may scale
  with sequence length, vocab, or pool size. The dynamic transfer guard
  proves zero D2H happens *inside* a frame; this rule bounds the SIZE of
  what crosses at the boundary.
- **GL204 redundant collectives** — the same operand reduced twice over
  the same axis, a collective applied to an already replica-invariant
  value, or an all-gather whose result is summed straight back down:
  N x the wire bytes for a value one collective computes.

Counting rules (the golden-value tests in ``tests/test_cost_model.py`` pin
these exactly — change them only together):

- **FLOPs** count ``dot_general``/``conv_general_dilated`` only
  (2 x batch x M x N x K): the roofline numerator. Elementwise work is
  deliberately excluded.
- **HBM bytes** are modeled per eqn as operand bytes read + result bytes
  written, times the eqn's execution multiplicity (the product of
  enclosing scan trip counts). A buffer is charged at the multiplicity it
  was *produced* at, so loop-invariant inputs — the params, a scan's
  consts and stacked xs — are charged ONCE per frame while carries (the
  KV pools) are charged per step: the scan-carry analysis behind "param
  bytes count once per frame".
- **Collective payload** is the wire bytes each device SENDS under the
  standard ring schedule: ``psum`` = 2(N-1)/N x bytes, ``all_gather`` =
  (N-1) x shard bytes, ``reduce_scatter``/``all_to_all`` = (N-1)/N x
  bytes, ``ppermute`` = bytes. This (not "operand bytes") is what makes
  GL202's identities exact: a psum decomposed into 2(N-1) ppermute chunks
  of bytes/N costs the same wire as the psum itself.
- Inside ``shard_map`` avals are per-shard, so every metric is PER DEVICE.
- ``while_loop`` trip counts are unknown statically: the body is charged
  once and ``unbounded_loops`` is flagged in the report.
- ``cond`` branches charge the elementwise MAX across branches.

Like the findings baseline, the cost baseline is content-addressed per
program: keyed by registry name (which encodes shape bucket, tp degree and
lowering variant), never by source position.
"""

import dataclasses
import json
import math
import os
import re
from typing import Dict, List, Optional, Sequence

from .findings import Finding
from .jaxpr_checks import (JAXPR_PATH, TracedProgram, _axis_names, _closed,
                           _trace_failure)

COST_BASELINE_VERSION = 1
#: the committed ledger at the repo root (three levels up from analysis/)
COST_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".graft-cost-baseline.json")
#: relative drift per metric before GL201 fires. Static costs are exact —
#: the tolerance only absorbs deliberate tiny-constant churn (a new stat
#: lane, one more boundary flag), not real growth.
DEFAULT_TOLERANCE = 0.02

#: wire bytes each device sends, as a fraction of operand bytes, under the
#: standard ring schedule (N = product of the named axis sizes)
_WIRE_FACTOR = {
    "psum": lambda n: 2 * (n - 1) / n,
    "pmax": lambda n: 2 * (n - 1) / n,
    "pmin": lambda n: 2 * (n - 1) / n,
    "all_gather": lambda n: n - 1,          # operand = the local shard
    "reduce_scatter": lambda n: (n - 1) / n,
    "psum_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "pbroadcast": lambda n: 1.0,
}

_CALL_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:     # tokens etc.
        return 0
    return int(math.prod(shape)) * dtype.itemsize


def _is_literal(v) -> bool:
    return not hasattr(v, "count")         # jax.core.Literal has no .count


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


class _Measurer:
    """One pass over a ClosedJaxpr accumulating the cost metrics.

    ``env`` maps each Var to the multiplicity it was PRODUCED at; a read
    is charged at ``min(birth, reader multiplicity)``, which is what makes
    loop-invariant operands (scan consts/xs — the params) count once per
    frame while carries count per step."""

    def __init__(self):
        self.flops = 0
        self.hbm_read = 0.0
        self.hbm_write = 0.0
        self.coll_ops: Dict[str, int] = {}
        self.coll_payload: Dict[str, float] = {}
        self.payload_by_dtype: Dict[str, float] = {}
        self.unbounded_loops = 0

    # -- var bookkeeping ----------------------------------------------------

    def _birth(self, env, v, mult):
        if _is_literal(v):
            return mult
        return env.get(v, mult)

    def _charge_reads(self, env, invars, mult):
        self.hbm_read += sum(
            _aval_bytes(v.aval) * min(self._birth(env, v, mult), mult)
            for v in invars)

    def _bind(self, env, outvars, mult):
        for v in outvars:
            env[v] = mult

    # -- entry --------------------------------------------------------------

    def measure(self, closed):
        jaxpr = closed.jaxpr
        env = {}
        for v in jaxpr.invars:
            env[v] = 1
        for v in jaxpr.constvars:
            env[v] = 1
        self._walk(jaxpr, env, 1, {})

    def _walk(self, jaxpr, env, mult, axis_sizes):
        for cv in jaxpr.constvars:
            env.setdefault(cv, 1)
        for eqn in jaxpr.eqns:
            p = eqn.primitive.name
            if p == "scan":
                self._scan(eqn, env, mult, axis_sizes)
            elif p == "while":
                self._while(eqn, env, mult, axis_sizes)
            elif p == "cond":
                self._cond(eqn, env, mult, axis_sizes)
            elif p == "shard_map":
                self._shard_map(eqn, env, mult, axis_sizes)
            elif any(hasattr(eqn.params.get(k), "jaxpr")
                     or hasattr(eqn.params.get(k), "eqns")
                     for k in _CALL_JAXPR_KEYS):
                self._call(eqn, env, mult, axis_sizes)
            else:
                self._leaf(eqn, env, mult, axis_sizes)

    # -- structured primitives ----------------------------------------------

    def _scan(self, eqn, env, mult, axis_sizes):
        trip = int(eqn.params["length"])
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        # consts + stacked xs are consumed once per scan EXECUTION — the
        # "params count once per frame" rule; the init carry is charged by
        # the first iteration's body read
        self._charge_reads(env, eqn.invars[:nc], mult)
        self._charge_reads(env, eqn.invars[nc + ncar:], mult)
        body = eqn.params["jaxpr"].jaxpr
        benv = dict(env)
        bviews = body.invars
        for bv in bviews[:nc]:
            benv[bv] = 0                   # already charged at the eqn
        for bv in bviews[nc:nc + ncar]:
            benv[bv] = mult * trip         # a fresh carry every iteration
        for bv in bviews[nc + ncar:]:
            benv[bv] = 0                   # the stacked xs were charged once
        self._walk(body, benv, mult * trip, axis_sizes)
        self._bind(env, eqn.outvars, mult)

    def _while(self, eqn, env, mult, axis_sizes):
        # trip count is dynamic: charge ONE trip and flag it — a serving
        # program should never contain one (scan with static length is the
        # compiled-loop idiom), so the report makes it visible
        self.unbounded_loops += 1
        cn = eqn.params["cond_nconsts"]
        bn = eqn.params["body_nconsts"]
        self._charge_reads(env, eqn.invars, mult)
        for inner, consts, carry in (
                (eqn.params["cond_jaxpr"].jaxpr, eqn.invars[:cn],
                 eqn.invars[cn + bn:]),
                (eqn.params["body_jaxpr"].jaxpr, eqn.invars[cn:cn + bn],
                 eqn.invars[cn + bn:])):
            benv = dict(env)
            for bv in inner.invars:
                benv[bv] = 0
            self._walk(inner, benv, mult, axis_sizes)
        self._bind(env, eqn.outvars, mult)

    def _cond(self, eqn, env, mult, axis_sizes):
        self._charge_reads(env, eqn.invars, mult)
        branch_costs = []
        for br in eqn.params["branches"]:
            sub = _Measurer()
            benv = {}
            for bv, ov in zip(br.jaxpr.invars, eqn.invars[1:]):
                benv[bv] = 0               # operands charged at the eqn
            sub._walk(br.jaxpr, benv, mult, axis_sizes)
            branch_costs.append(sub)
        self._merge_max(branch_costs)
        self._bind(env, eqn.outvars, mult)

    def _merge_max(self, subs: Sequence["_Measurer"]):
        if not subs:
            return
        self.flops += max(s.flops for s in subs)
        self.hbm_read += max(s.hbm_read for s in subs)
        self.hbm_write += max(s.hbm_write for s in subs)
        self.unbounded_loops += max(s.unbounded_loops for s in subs)
        for attr in ("coll_ops", "coll_payload", "payload_by_dtype"):
            mine = getattr(self, attr)
            for key in {k for s in subs for k in getattr(s, attr)}:
                mine[key] = mine.get(key, 0) + max(
                    getattr(s, attr).get(key, 0) for s in subs)

    def _shard_map(self, eqn, env, mult, axis_sizes):
        mesh = eqn.params["mesh"]
        sizes = {**axis_sizes,
                 **{name: int(size) for name, size in
                    zip(mesh.axis_names, mesh.devices.shape)}}
        body = eqn.params["jaxpr"]
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        benv = dict(env)
        for bv, ov in zip(body.invars, eqn.invars):
            benv[bv] = self._birth(env, ov, mult)
        self._walk(body, benv, mult, sizes)
        self._bind(env, eqn.outvars, mult)

    def _call(self, eqn, env, mult, axis_sizes):
        inner = next(eqn.params[k] for k in _CALL_JAXPR_KEYS
                     if k in eqn.params)
        body = inner.jaxpr if hasattr(inner, "jaxpr") else inner
        benv = dict(env)
        for bv, ov in zip(body.invars, eqn.invars):
            benv[bv] = self._birth(env, ov, mult)
        self._walk(body, benv, mult, axis_sizes)
        self._bind(env, eqn.outvars, mult)

    # -- leaf primitives ----------------------------------------------------

    def _leaf(self, eqn, env, mult, axis_sizes):
        p = eqn.primitive.name
        self._charge_reads(env, eqn.invars, mult)
        self.hbm_write += sum(_aval_bytes(v.aval) for v in eqn.outvars) * mult
        if p == "dot_general":
            self.flops += self._dot_flops(eqn) * mult
        elif p == "conv_general_dilated":
            self.flops += self._conv_flops(eqn) * mult
        if p in _WIRE_FACTOR:
            self._collective(eqn, mult, axis_sizes)
        self._bind(env, eqn.outvars, mult)

    @staticmethod
    def _dot_flops(eqn) -> int:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval.shape
        rhs = eqn.invars[1].aval.shape
        batch = math.prod(lhs[i] for i in lb)
        contract = math.prod(lhs[i] for i in lc)
        m = math.prod(lhs[i] for i in range(len(lhs))
                      if i not in set(lb) | set(lc))
        n = math.prod(rhs[i] for i in range(len(rhs))
                      if i not in set(rb) | set(rc))
        return 2 * batch * m * n * contract

    @staticmethod
    def _conv_flops(eqn) -> int:
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval.shape
        out = eqn.outvars[0].aval.shape
        groups = eqn.params.get("feature_group_count", 1)
        spatial = math.prod(rhs[i] for i in dn.rhs_spec[2:])
        in_ch = rhs[dn.rhs_spec[1]]
        return 2 * math.prod(out) * in_ch * spatial // max(groups, 1)

    def _collective(self, eqn, mult, axis_sizes):
        axes = [ax for ax in _axis_names(eqn) if ax in axis_sizes]
        if not axes:
            return
        n = math.prod(axis_sizes[ax] for ax in axes)
        if n <= 1:
            return
        operand_bytes = sum(_aval_bytes(v.aval) for v in eqn.invars)
        payload = _WIRE_FACTOR[eqn.primitive.name](n) * operand_bytes * mult
        key = "+".join(sorted(axes))
        self.coll_ops[key] = self.coll_ops.get(key, 0) + mult
        self.coll_payload[key] = self.coll_payload.get(key, 0) + payload
        dt = str(eqn.invars[0].aval.dtype)
        self.payload_by_dtype[dt] = self.payload_by_dtype.get(dt, 0) + payload


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

#: program base name -> flat output indices the HOST materializes at the
#: frame boundary (np.asarray in run_frame / stats_delta / nonfinite_uids /
#: resync_committed). Maintained exactly like ast_checks.DISPATCH_DONATIONS:
#: tests/test_cost_model.py cross-checks shapes against the live traces so
#: a loop that grows an output cannot silently rot the table.
HOST_READ_OUTPUTS: Dict[str, Sequence[int]] = {
    # (toks, emit, cached, produced, last_tok, done, poison, nonfinite,
    #  stats, rng, k, v)
    "frame_loop": (0, 1, 2, 7, 8),
    # (toks, emit, cached, produced, last_tok, penult, done, poison,
    #  nonfinite, stats, rng, k, v, dk, dv)
    "frame_loop_spec": (0, 1, 2, 8, 9),
    "mixed_loop": (0, 1),                  # (toks, emit, k, v)
    "mixed_loop_spec": (0, 1),
    "decode_loop": (0,),                   # (toks, k, v)
    "run": (0,),                           # host-step path reads its logits
    "copy_blocks": (),                     # donated pools only
    "scatter_pages": (),
    "gather_pages": (0, 1),                # swap-out D2H-reads the pages
}

#: the frame/mixed/decode loops carry the GL203 budget; `run` (the chunked
#: host-step path reads (B, V) logits by contract) and the page movers
#: (gather_pages IS a bulk D2H, that's its job) are reported but not gated
D2H_BUDGET_SCOPE = ("frame_loop", "frame_loop_spec", "mixed_loop",
                    "mixed_loop_spec", "decode_loop")

#: bytes of per-row boundary lanes GL203 allows beyond the emission stream
#: (cached/produced watermarks, latches, a stats row): 16 int32 lanes. The
#: flat slack stays SMALL relative to the tiny registry shapes (B=4) so a
#: seq-len-scaled leak of even a few hundred bytes per row still trips the
#: budget at lint scale, not just at production scale.
_D2H_ROW_ALLOWANCE = 64
_D2H_SLACK = 128


@dataclasses.dataclass
class CostReport:
    """Per-device static cost of one traced serving program."""
    name: str
    variant: str
    counterpart: str
    flops: int
    hbm_read: int
    hbm_write: int
    d2h_bytes: int
    coll_ops: Dict[str, int]
    coll_payload: Dict[str, int]           # mesh axis -> wire bytes
    payload_by_dtype: Dict[str, int]
    unbounded_loops: int = 0

    @property
    def total_payload(self) -> int:
        return sum(self.coll_payload.values())

    @property
    def int8_payload(self) -> int:
        """One-byte quantized wire bytes: int8 AND fp8 (e4m3/e5m2)
        collective operands — both payload formats of the quantized
        lowering, identical width, so GL202's <=0.5x-of-exact contract
        applies to either. The metric keeps its historical
        ``collective_payload_int8`` name (baseline schema)."""
        return sum(v for k, v in self.payload_by_dtype.items()
                   if k == "int8" or k.startswith("float8"))

    def metrics(self) -> Dict[str, int]:
        """The flat metric dict GL201 diffs against the baseline."""
        return {
            "flops": self.flops,
            "hbm_read": self.hbm_read,
            "hbm_write": self.hbm_write,
            "d2h_bytes": self.d2h_bytes,
            "collective_ops": sum(self.coll_ops.values()),
            "collective_payload": self.total_payload,
            "collective_payload_int8": self.int8_payload,
        }

    def as_json(self) -> Dict:
        return {"name": self.name, "variant": self.variant,
                **self.metrics(),
                "collectives_by_axis": dict(sorted(self.coll_payload.items())),
                "payload_by_dtype": dict(sorted(
                    self.payload_by_dtype.items())),
                "unbounded_loops": self.unbounded_loops}


def _base_name(name: str) -> str:
    return name.split("[")[0]


def measure_jaxpr(closed) -> _Measurer:
    m = _Measurer()
    m.measure(closed)
    return m


def measure_program(prog: TracedProgram) -> Optional[CostReport]:
    """Interpret one traced program into a CostReport; ``None`` when the
    trace fails (GL000 from the jaxpr family already owns that)."""
    if _trace_failure(prog) is not None:
        return None
    closed = _closed(prog.traced())
    m = measure_jaxpr(closed)
    reads = HOST_READ_OUTPUTS.get(_base_name(prog.name), ())
    out_avals = list(closed.out_avals)
    d2h = sum(_aval_bytes(out_avals[i]) for i in reads
              if i < len(out_avals))
    return CostReport(
        name=prog.name, variant=prog.variant,
        counterpart=prog.counterpart, flops=int(m.flops),
        hbm_read=int(round(m.hbm_read)), hbm_write=int(round(m.hbm_write)),
        d2h_bytes=int(d2h),
        coll_ops={k: int(v) for k, v in sorted(m.coll_ops.items())},
        coll_payload={k: int(round(v))
                      for k, v in sorted(m.coll_payload.items())},
        payload_by_dtype={k: int(round(v))
                          for k, v in sorted(m.payload_by_dtype.items())},
        unbounded_loops=m.unbounded_loops)


# ---------------------------------------------------------------------------
# GL201 — cost regression vs the committed baseline
# ---------------------------------------------------------------------------


def load_cost_baseline(path: str) -> Dict:
    with open(path) as fh:
        data = json.load(fh)
    if data.get("version") != COST_BASELINE_VERSION:
        raise ValueError(f"{path}: unrecognized cost-baseline version "
                         f"{data.get('version')!r}")
    return data


def write_cost_baseline(path: str, reports: List[CostReport],
                        tolerance: float = DEFAULT_TOLERANCE) -> None:
    data = {"version": COST_BASELINE_VERSION, "tolerance": tolerance,
            "programs": {r.name: r.metrics()
                         for r in sorted(reports, key=lambda r: r.name)}}
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def check_cost_baseline(reports: List[CostReport], baseline: Dict,
                        include_tp: bool = True) -> List[Finding]:
    """GL201: every metric of every program within tolerance of the
    committed baseline — growth is a regression, shrink is a stale
    baseline; both need an explicit ``--update-cost-baseline``."""
    tol = float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    base = baseline.get("programs", {})
    findings = []
    seen = set()
    for r in reports:
        seen.add(r.name)
        b = base.get(r.name)
        if b is None:
            findings.append(Finding(
                "GL201", JAXPR_PATH, 0,
                "program has no cost-baseline entry — a new serving "
                "program lands with its costs recorded "
                "(--update-cost-baseline) so the next PR diffs against "
                "them", context=r.name))
            continue
        for key, val in r.metrics().items():
            bval = b.get(key)
            if bval is None:
                findings.append(Finding(
                    "GL201", JAXPR_PATH, 0,
                    f"metric '{key}' missing from the cost baseline — "
                    "re-record with --update-cost-baseline",
                    context=r.name))
                continue
            if abs(val - bval) > tol * max(abs(bval), 1):
                direction = "grew" if val > bval else "shrank"
                pct = (100.0 * (val - bval) / bval) if bval else float("inf")
                findings.append(Finding(
                    "GL201", JAXPR_PATH, 0,
                    f"{key} {direction} beyond tolerance: baseline {bval}, "
                    f"now {val} ({pct:+.1f}%, tolerance "
                    f"{tol:.1%}) — explain the change in the PR and "
                    "re-record with --update-cost-baseline",
                    context=r.name))
    for name in sorted(set(base) - seen):
        if not include_tp and "[tp=8" in name:
            continue            # --no-tp run: tp entries legitimately absent
        findings.append(Finding(
            "GL201", JAXPR_PATH, 0,
            "stale cost-baseline entry: program is no longer traced by the "
            "registry — remove it with --update-cost-baseline (or restore "
            "its registration)", context=name))
    return findings


# ---------------------------------------------------------------------------
# GL202 — quantized / overlap payload contracts
# ---------------------------------------------------------------------------


def check_collective_contracts(reports: List[CostReport]) -> List[Finding]:
    by_name = {r.name: r for r in reports}
    findings = []
    for r in reports:
        if r.variant == "exact":
            continue
        exact = by_name.get(r.counterpart)
        if exact is None:
            findings.append(Finding(
                "GL202", JAXPR_PATH, 0,
                f"{r.variant} variant has no exact counterpart in the "
                "registry — the payload contract cannot be checked",
                context=r.name))
            continue
        etotal = exact.total_payload
        if r.variant == "quantized":
            findings.extend(_check_quantized(r, exact, etotal))
        elif r.variant == "overlap":
            findings.extend(_check_overlap(r, exact, etotal))
    return findings


def _check_quantized(r: CostReport, exact: CostReport,
                     etotal: int) -> List[Finding]:
    out = []
    if r.int8_payload == 0:
        out.append(Finding(
            "GL202", JAXPR_PATH, 0,
            "tp_quantized_collectives is set but the traced program "
            "exchanges no int8 payload — the flag is dead weight",
            context=r.name))
        return out
    if etotal and r.int8_payload > 0.5 * etotal:
        out.append(Finding(
            "GL202", JAXPR_PATH, 0,
            f"int8 wire bytes {r.int8_payload} exceed 0.5x the exact "
            f"program's total collective payload ({etotal}): the "
            "quantized lowering moves more than half the traffic it "
            "claims to halve (ratio "
            f"{r.int8_payload / etotal:.3f})", context=r.name))
    if etotal and r.total_payload >= etotal:
        out.append(Finding(
            "GL202", JAXPR_PATH, 0,
            f"total collective payload {r.total_payload} (int8 "
            f"{r.int8_payload} + scales/exact remainder "
            f"{r.total_payload - r.int8_payload}) is not below the exact "
            f"program's {etotal}: quantization buys no net traffic",
            context=r.name))
    return out


def _check_overlap(r: CostReport, exact: CostReport,
                   etotal: int) -> List[Finding]:
    # the T3 ring must carry EXACTLY the exact psum's wire bytes:
    # 2(N-1) ppermute hops x (bytes/N) chunks == 2(N-1)/N x bytes. More
    # means redundant chunks; less means the ring drops data.
    if math.isclose(r.total_payload, etotal, rel_tol=1e-9, abs_tol=8):
        return []
    return [Finding(
        "GL202", JAXPR_PATH, 0,
        f"ring-overlap total wire bytes {r.total_payload} != exact "
        f"program's {etotal}: the 2(N-1)-chunk ppermute decomposition no "
        "longer carries the full all-reduce payload (a chunking bug — "
        "too many hops, or dropped chunks)", context=r.name)]


# ---------------------------------------------------------------------------
# GL203 — boundary D2H budget
# ---------------------------------------------------------------------------


def check_d2h_budget(report: CostReport, prog: TracedProgram
                     ) -> List[Finding]:
    base = _base_name(report.name)
    if base not in D2H_BUDGET_SCOPE or _trace_failure(prog) is not None:
        return []
    out_avals = list(_closed(prog.traced()).out_avals)
    reads = HOST_READ_OUTPUTS[base]
    if any(i >= len(out_avals) for i in reads):
        return [Finding(
            "GL203", JAXPR_PATH, 0,
            f"HOST_READ_OUTPUTS indexes output {max(reads)} but the "
            f"program has {len(out_avals)} outputs — the table drifted "
            "from the loop's return signature", context=report.name)]
    toks = out_avals[0]
    batch = toks.shape[1] if len(toks.shape) > 1 else 1
    stream = _aval_bytes(toks)
    if len(reads) > 1 and 1 in reads:
        stream += _aval_bytes(out_avals[1])          # the emit mask
    budget = stream + _D2H_ROW_ALLOWANCE * batch + _D2H_SLACK
    if report.d2h_bytes <= budget:
        return []
    return [Finding(
        "GL203", JAXPR_PATH, 0,
        f"host-read outputs total {report.d2h_bytes} bytes per frame, over "
        f"the boundary budget of {budget} (emission stream {stream} + "
        f"{_D2H_ROW_ALLOWANCE}/row x {batch} rows + {_D2H_SLACK} slack): "
        "a host-read output scales with something other than the batch — "
        "sequence length, vocab, or pool size crossing the boundary every "
        "frame", context=report.name)]


# ---------------------------------------------------------------------------
# GL204 — redundant collectives
# ---------------------------------------------------------------------------

#: value-preserving ops a gathered result may pass through before a
#: reduction still counts as "immediately reduced" (exp/softmax chains are
#: deliberately NOT here: a softmax over gathered logits is legitimate)
_PASSTHROUGH = {"convert_element_type", "mul", "add", "sub", "neg",
                "reshape", "transpose", "broadcast_in_dim"}
_MAX_CHAIN = 3


def check_redundant_collectives(prog: TracedProgram) -> List[Finding]:
    if _trace_failure(prog) is not None:
        return []
    findings: List[Finding] = []
    _scan_redundant(_closed(prog.traced()).jaxpr, prog.name, findings, {})
    return findings


def _scan_redundant(jaxpr, prog_name: str, findings: List[Finding],
                    axis_sizes: Dict[str, int]) -> None:
    seen_psums = set()              # (operand var, axes) already reduced
    invariant = {}                  # var -> axes it is replica-invariant over
    gather_chain = {}               # var -> (hops since all_gather, degree N)
    for eqn in jaxpr.eqns:
        p = eqn.primitive.name
        if p == "shard_map":
            mesh = eqn.params["mesh"]
            axis_sizes = {**axis_sizes,
                          **{name: int(size) for name, size in
                             zip(mesh.axis_names, mesh.devices.shape)}}
        axes = frozenset(_axis_names(eqn))
        if p == "psum" and axes:
            for v in eqn.invars:
                if _is_literal(v):
                    continue
                key = (v, axes)
                if key in seen_psums:
                    findings.append(Finding(
                        "GL204", JAXPR_PATH, 0,
                        f"the same operand is psummed twice over axis "
                        f"{sorted(axes)} — one all-reduce computes it; the "
                        "second doubles the wire bytes for an identical "
                        "value", context=prog_name))
                seen_psums.add(key)
                if axes & invariant.get(v, frozenset()):
                    findings.append(Finding(
                        "GL204", JAXPR_PATH, 0,
                        f"psum over {sorted(axes)} of a value that is "
                        "already replica-invariant on that axis (the "
                        "output of a psum/all_gather): this multiplies by "
                        "the axis size — almost certainly a double-"
                        "reduction bug", context=prog_name))
        if p in ("psum", "pmax", "pmin", "all_gather") and axes:
            for o in eqn.outvars:
                invariant[o] = axes | invariant.get(o, frozenset())
        if p == "all_gather" and axes:
            degree = math.prod(axis_sizes.get(ax, 1) for ax in axes)
            if degree > 1:
                for o in eqn.outvars:
                    gather_chain[o] = (0, degree)
        elif p in _PASSTHROUGH:
            tagged = [gather_chain[v] for v in eqn.invars
                      if not _is_literal(v) and v in gather_chain]
            if tagged and min(t[0] for t in tagged) < _MAX_CHAIN:
                hops, degree = min(tagged)
                for o in eqn.outvars:
                    gather_chain[o] = (hops + 1, degree)
        elif p == "reduce_sum":
            # only a reduction that collapses the gather-degree extent is
            # the redundant shape — summing a gathered tensor over an
            # unrelated dim (a feature-dim norm, say) is legitimate
            for v in eqn.invars:
                if _is_literal(v) or v not in gather_chain:
                    continue
                _, degree = gather_chain[v]
                shape = getattr(v.aval, "shape", ())
                reduced = [shape[ax] for ax in eqn.params.get("axes", ())
                           if ax < len(shape)]
                if any(ext == degree for ext in reduced):
                    findings.append(Finding(
                        "GL204", JAXPR_PATH, 0,
                        "an all-gather's result is summed straight back "
                        "down (gather -> elementwise -> reduce_sum over "
                        "the gathered extent): this moves (N-1)x the "
                        "bytes of the reduce-scatter/psum that computes "
                        "the same value", context=prog_name))
        for sub in _subjaxprs_of(eqn):
            _scan_redundant(sub, prog_name, findings, axis_sizes)


def _subjaxprs_of(eqn):
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if hasattr(v, "eqns"):
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr


# ---------------------------------------------------------------------------
# the gate + the report table
# ---------------------------------------------------------------------------


def run_cost_checks(progs: List[TracedProgram],
                    baseline: Optional[Dict] = None,
                    include_tp: bool = True):
    """Family C in one call: measure every program, then GL201 (when a
    baseline is given), GL202, GL203, GL204. Returns (findings, reports).
    Programs that fail to trace yield no report — the jaxpr family's GL000
    owns surfacing that."""
    findings: List[Finding] = []
    reports: List[CostReport] = []
    for prog in progs:
        rep = measure_program(prog)
        if rep is None:
            continue
        reports.append(rep)
        findings.extend(check_d2h_budget(rep, prog))
        findings.extend(check_redundant_collectives(prog))
    findings.extend(check_collective_contracts(reports))
    if baseline is not None:
        findings.extend(check_cost_baseline(reports, baseline,
                                            include_tp=include_tp))
    return findings, reports


def render_cost_table(reports: List[CostReport]) -> str:
    """Markdown table of every program's cost metrics (``--cost-report``)."""
    headers = ("program", "flops", "hbm_read", "hbm_write",
               "coll_payload", "coll_ops", "d2h_bytes")
    rows = [headers, tuple("---" for _ in headers)]
    for r in sorted(reports, key=lambda r: r.name):
        rows.append((r.name, f"{r.flops:,}", f"{r.hbm_read:,}",
                     f"{r.hbm_write:,}", f"{r.total_payload:,}",
                     str(sum(r.coll_ops.values())), f"{r.d2h_bytes:,}"))
    widths = [max(len(row[i]) for row in rows) for i in range(len(headers))]
    return "\n".join(
        "| " + " | ".join(cell.ljust(w) for cell, w in zip(row, widths))
        + " |" for row in rows)


# ----------------------------------------------------------------------
# frame-cost QUERY API over a committed baseline (the simulator's
# price list: sim/ replays traffic with frame costs read from HERE —
# no frames executed)
# ----------------------------------------------------------------------

# metric keys every ledger entry carries (CostReport.metrics())
COST_METRIC_KEYS = ("flops", "hbm_read", "hbm_write", "d2h_bytes",
                    "collective_ops", "collective_payload",
                    "collective_payload_int8")


class FrameCostQuery:
    """Query API over one committed ``.graft-cost-baseline.json``.

    The ledger prices every traced serving program statically (GL201) —
    this class makes it QUERYABLE by frame shape instead of by exact
    program name: ``select(width=8, spec=True, tp=8, quant=True)``
    resolves to ``frame_loop_spec[w=8][tp=8,quant]`` and returns its
    FLOPs / HBM bytes / collective wire bytes. The trace-driven fleet
    simulator prices every virtual frame through here; a kernel change
    that shifts the ledger shifts the sim's capacity answers with it.
    """

    def __init__(self, baseline: Dict):
        if baseline.get("version") != COST_BASELINE_VERSION:
            raise ValueError(
                f"cost baseline version {baseline.get('version')!r} != "
                f"{COST_BASELINE_VERSION}")
        self.programs: Dict[str, Dict] = baseline["programs"]
        self._widths = sorted({
            int(m.group(1)) for name in self.programs
            for m in [re.search(r"\[w=(\d+)[,\]]", name)] if m})

    @classmethod
    def load(cls, path: str = COST_BASELINE_PATH) -> "FrameCostQuery":
        return cls(load_cost_baseline(path))

    def metrics(self, name: str) -> Dict[str, float]:
        """Ledger metrics for one exact program name (KeyError with the
        available names when absent — a renamed program must fail loudly,
        not price frames at zero)."""
        try:
            return self.programs[name]
        except KeyError:
            raise KeyError(
                f"program {name!r} not in the cost baseline; available: "
                f"{sorted(self.programs)}") from None

    def frame_program(self, *, width: int = 1, spec: bool = False,
                      tp: int = 1, quant: bool = False, fp8: bool = False,
                      ring: bool = False, repair: bool = False) -> str:
        """Resolve a frame SHAPE to the ledger's program name.

        ``width`` snaps to the nearest traced width bucket (the ledger
        traces one narrow and one wide frame_loop; chunked-prefill frames
        of any chunk size price from the wide bucket — the calibration
        layer in ``sim.cost`` scales by the actual width). Exactly one of
        the tp-variant flags (quant/fp8/ring) may be set with tp > 1."""
        if not self._widths:
            raise ValueError("cost baseline has no frame_loop[w=...] "
                             "programs to price frames from")
        w = min(self._widths, key=lambda b: (abs(b - width), b))
        base = "frame_loop_spec" if spec else "frame_loop"
        head = f"{base}[w={w},repair]" if repair else f"{base}[w={w}]"
        if tp > 1:
            variant = ("quant" if quant else "fp8" if fp8
                       else "ring" if ring else None)
            suffix = f"[tp={tp},{variant}]" if variant else f"[tp={tp}]"
        else:
            suffix = "[quant]" if quant else ""
        name = head + suffix
        if name not in self.programs and tp > 1:
            # heterogeneous ledgers may trace one tp degree only — fall
            # back to the traced tp suffix rather than KeyError on e.g.
            # tp=4 when only tp=8 was traced (the calibration constants
            # absorb the degree difference)
            tail = f",{variant}]" if variant else "]"
            cands = [n for n in self.programs
                     if n.startswith(head + "[tp=") and n.endswith(tail)
                     and (variant or "," not in n[len(head):])]
            if cands:
                name = sorted(cands)[0]
        return name

    def select(self, **shape) -> Dict[str, float]:
        """``metrics(frame_program(**shape))`` — the one-call form."""
        return self.metrics(self.frame_program(**shape))
