"""graft-lint: static analysis for the compiled serving stack.

Six PRs of serving work (frame loop → speculation → telemetry → scheduler →
faults → tensor parallelism) rest on invariants that were only checked
*dynamically* — a transfer guard around ``dispatch_frame``, recompile-count
assertions, tp parity suites. This package checks the same invariants
*statically*, before a test run or a pod-slice deploy:

- **Family A (jaxpr)** — trace the real serving programs on tiny abstract
  shapes and walk the resulting ClosedJaxprs: no host-sync primitives
  inside frames (GL001), donation-safe carry handoffs (GL002),
  well-formed shard_map collectives and replica-invariant replicated
  outputs (GL003), and trace-deterministic entry points (GL004).
- **Family B (AST)** — lint ``deepspeed_tpu/`` source for retrace hazards:
  Python branching on tracer values (GL101), unhashable static arguments
  (GL102), dtype-promotion drift (GL103), host coercions in jitted code
  (GL104), ``print`` in jitted code (GL105).
- **Family C (graft-cost)** — interpret the same traced programs into a
  quantitative per-program cost ledger (matmul FLOPs, HBM bytes, per-axis
  collective wire bytes, boundary D2H bytes) and gate it: regression vs
  the committed ``.graft-cost-baseline.json`` (GL201), the quantized/ring
  collective payload contracts (GL202), the O(batch) frame-boundary
  transfer budget (GL203), and redundant-collective detection (GL204).

CLI: ``python -m deepspeed_tpu.analysis.lint deepspeed_tpu/`` (or
``bin/dstpu_lint``; ``--cost-report`` for the per-program table,
``--update-cost-baseline`` to re-record the ledger). See README "Static
analysis".
"""

from .findings import (Finding, RULES, load_baseline, write_baseline,
                       filter_baseline, suppressed_lines)

__all__ = ["Finding", "RULES", "load_baseline", "write_baseline",
           "filter_baseline", "suppressed_lines"]
