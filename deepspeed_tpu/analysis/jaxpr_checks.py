"""Family A: jaxpr-level invariant checks for the compiled serving stack.

Each check consumes a ``TracedProgram`` — a lazily-traced serving entry
point (``programs.build_serving_programs`` traces the REAL frame loops on
tiny abstract shapes; the test fixtures trace deliberately-broken ones) —
and walks the resulting ``ClosedJaxpr``:

- **GL001 TransferGuard** — no host-sync primitive (callbacks, debug
  prints, infeed/outfeed) anywhere in a serving program; scan bodies are
  reported as such. A trace that dies on an implicit ``np.*`` coercion
  (TracerArrayConversionError) is the same bug caught earlier and is
  reported under the same rule.
- **GL002 DonationChecker** — every donated input aval has a matching
  output aval (a donated buffer with no same-shape/dtype output is never
  reused by XLA: the donation silently buys nothing and the caller still
  loses the buffer).
- **GL003 CollectiveChecker** — inside ``shard_map`` manual regions:
  every collective names an axis that is manual on the enclosing mesh,
  every ``ppermute`` permutation is a true permutation (distinct sources,
  distinct targets, no data created or lost), and every output DECLARED
  replicated (empty out_names) is replica-invariant by dataflow — a taint
  pass seeded at sharded inputs and ``axis_index``, cleared only by a
  collective reduction over the tainted axis. This is the static
  replacement for the ``check_rep=False`` the frame loops compile with.
  Scope note: a *dropped* psum whose surrounding program still reduces
  later produces replica-invariant-but-WRONG values — that is a parity
  bug the dynamic token-parity suites own; this pass owns replica
  VARIANCE (e.g. a dropped logit all-gather, where each shard argmaxes
  its local vocab slice and the "replicated" carries silently fork).
- **GL004 RetraceBudget** — tracing the entry point twice with identical
  (bucket-compatible) shapes must produce byte-identical jaxprs; anything
  else is a retrace per call in production (the static complement of
  ``compile_count_total()``).
"""

import dataclasses
import os
import traceback
from typing import Callable, List, Optional, Sequence, Set

from .findings import Finding

JAXPR_PATH = "<jaxpr>"     # pseudo-path for program-level findings

#: primitives that synchronize with / call back into the host
HOST_SYNC_PRIMITIVES = {
    "debug_callback", "pure_callback", "io_callback", "callback",
    "outside_call", "infeed", "outfeed", "host_callback_call",
}

#: collective primitives and the param carrying their axis name(s)
_COLLECTIVE_AXIS_PARAM = {
    "psum": "axes", "pmax": "axes", "pmin": "axes",
    "ppermute": "axis_name", "pbroadcast": "axis_name",
    "all_gather": "axis_name", "all_to_all": "axis_name",
    "reduce_scatter": "axis_name", "psum_scatter": "axis_name",
    "axis_index": "axis_name",
}
#: of those, the reductions that make their output replica-invariant over
#: the reduced axis (ppermute/axis_index/all_to_all do NOT)
_INVARIANT_MAKERS = {"psum", "pmax", "pmin", "all_gather"}


@dataclasses.dataclass
class TracedProgram:
    """A serving entry point plus everything the checks need.

    ``trace`` runs the actual ``jax.jit(...).trace(...)`` (or
    ``jax.make_jaxpr``) lazily: trace-time failures are findings, not
    crashes — an implicit host coercion raises TracerArrayConversionError
    (GL001) and an unbound collective axis raises NameError (GL003).
    ``retrace`` must rebuild the jit from scratch so the comparison cannot
    be satisfied by a cache hit.

    ``variant``/``counterpart`` exist for the Family C cost pass
    (``cost_model``): a program traced with a non-default collective
    lowering ("quantized"/"overlap") names the exact-collectives program
    it must be payload-compared against. The default registry is all
    ``variant="exact"``."""
    name: str
    trace: Callable[[], object]          # () -> object with .jaxpr
    donate_argnums: Sequence[int] = ()   # FLAT indices (match .in_avals)
    donate_user_args: Sequence[int] = ()  # user positional args (pytrees=1)
    retrace: Optional[Callable[[], object]] = None
    variant: str = "exact"               # "exact" | "quantized" | "overlap"
    counterpart: str = ""                # exact twin's name (cost variants)

    _traced: object = dataclasses.field(default=None, repr=False)
    _trace_error: Optional[BaseException] = dataclasses.field(
        default=None, repr=False)

    def traced(self):
        if self._traced is None and self._trace_error is None:
            try:
                self._traced = self.trace()
            except Exception as e:      # noqa: BLE001 — converted to findings
                self._trace_error = e
        if self._trace_error is not None:
            raise self._trace_error
        return self._traced


def _closed(traced):
    """Normalize a trace result to its ClosedJaxpr: accepts either a
    ``jax.stages.Traced`` (``.jaxpr`` is the ClosedJaxpr) or a ClosedJaxpr
    itself (``.jaxpr`` is the raw Jaxpr) — fixtures use ``jax.make_jaxpr``,
    the program registry uses ``jit(...).trace(...)``."""
    inner = traced.jaxpr
    return inner if hasattr(inner, "jaxpr") else traced


def _subjaxprs(params):
    """Yield every inner (jaxpr, primitive-param-key) of an eqn's params."""
    for key, val in params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if hasattr(v, "eqns"):                    # Jaxpr
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr                         # ClosedJaxpr


def _walk_eqns(jaxpr, in_scan=False):
    """DFS over every eqn in a jaxpr, yielding (eqn, inside_scan_body)."""
    for eqn in jaxpr.eqns:
        yield eqn, in_scan
        child_in_scan = in_scan or eqn.primitive.name in ("scan", "while")
        for sub in _subjaxprs(eqn.params):
            yield from _walk_eqns(sub, child_in_scan)


def _trace_failure(prog: TracedProgram) -> Optional[BaseException]:
    try:
        prog.traced()
        return None
    except Exception as e:               # noqa: BLE001
        return e


def failure_frame(err: BaseException) -> str:
    """``file.py:NN in fn`` for the most useful traceback frame of a trace
    failure: the INNERMOST frame inside this repo (the serving/analysis
    code that actually drifted), falling back to the innermost frame
    overall when the whole stack is framework-internal. A GL000 finding
    without this is near-undebuggable from the JSON output — the program
    name says *what* failed to trace, never *where*."""
    frames = traceback.extract_tb(err.__traceback__) if err.__traceback__ \
        else []
    if not frames:
        return "<no traceback>"
    here = os.path.abspath(__file__)           # this checker module only:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    pick = next(
        (f for f in reversed(frames)
         # the TracedProgram re-raise in THIS file is plumbing, never the
         # cause — but analysis/programs.py (registry shape drift) very
         # much can be, so only this module is excluded
         if os.path.abspath(f.filename).startswith(repo)
         and os.path.abspath(f.filename) != here),
        frames[-1])
    where = os.path.basename(pick.filename)
    return f"{where}:{pick.lineno} in {pick.name}"


# ---------------------------------------------------------------------------
# GL001 — TransferGuard
# ---------------------------------------------------------------------------

def check_transfer(prog: TracedProgram) -> List[Finding]:
    err = _trace_failure(prog)
    if err is not None:
        tname = type(err).__name__
        if "Tracer" in tname or "Concretization" in tname:
            return [Finding(
                "GL001", JAXPR_PATH, 0,
                f"tracing aborts with {tname}: an implicit host coercion "
                f"(np.*/float()/bool()) sits in the compiled path: {err}",
                context=prog.name)]
        return []     # unrelated trace failure: some other check owns it
    findings = []
    for eqn, in_scan in _walk_eqns(_closed(prog.traced()).jaxpr):
        pname = eqn.primitive.name
        if pname in HOST_SYNC_PRIMITIVES or pname.endswith("_callback"):
            where = ("inside a scan body — it fires EVERY step of every "
                     "frame" if in_scan else "in the frame program")
            findings.append(Finding(
                "GL001", JAXPR_PATH, 0,
                f"host-sync primitive `{pname}` {where}; the serving "
                "contract is zero in-frame device-to-host traffic",
                context=prog.name))
    return findings


# ---------------------------------------------------------------------------
# GL002 — DonationChecker (jaxpr half; ast_checks owns the dispatch sites)
# ---------------------------------------------------------------------------

def check_donation(prog: TracedProgram) -> List[Finding]:
    if _trace_failure(prog) is not None:
        return []
    tr = prog.traced()
    donate = tuple(prog.donate_argnums or getattr(tr, "donate_argnums", ()))
    if not donate:
        return []
    closed = _closed(tr)
    in_avals = tuple(closed.in_avals)
    outs = list(closed.out_avals)
    findings = []
    for i in donate:
        if i >= len(in_avals):
            findings.append(Finding(
                "GL002", JAXPR_PATH, 0,
                f"donate_argnums index {i} is out of range for the "
                f"{len(in_avals)} traced inputs (static-arg shift?)",
                context=prog.name))
            continue
        aval = in_avals[i]
        key = (aval.shape, aval.dtype)
        match = next((j for j, o in enumerate(outs)
                      if (o.shape, o.dtype) == key), None)
        if match is None:
            findings.append(Finding(
                "GL002", JAXPR_PATH, 0,
                f"donated input {i} ({aval.str_short()}) has no "
                "matching output aval: XLA cannot reuse the buffer, the "
                "donation is dead weight and the caller still loses the "
                "reference", context=prog.name))
        else:
            outs.pop(match)    # one output consumes one donation
    return findings


# ---------------------------------------------------------------------------
# GL003 — CollectiveChecker
# ---------------------------------------------------------------------------

def _axis_names(eqn) -> Sequence[str]:
    key = _COLLECTIVE_AXIS_PARAM.get(eqn.primitive.name)
    if key is None:
        return ()
    val = eqn.params.get(key)
    if val is None:
        return ()
    names = val if isinstance(val, (tuple, list)) else (val,)
    return [n for n in names if isinstance(n, str)]


def _taint_jaxpr(jaxpr, in_taints, manual_axes: Set[str]):
    """Forward taint pass: which outputs can differ across shards of the
    ``manual_axes``? Taints are per-var sets of axis names."""
    env = {}

    def read(v):
        return env.get(v, frozenset()) if hasattr(v, "count") else frozenset()

    for var, t in zip(jaxpr.invars, in_taints):
        env[var] = frozenset(t)
    for cv in jaxpr.constvars:
        env[cv] = frozenset()
    for eqn in jaxpr.eqns:
        pname = eqn.primitive.name
        in_taint = frozenset().union(*[read(v) for v in eqn.invars]) \
            if eqn.invars else frozenset()
        axes = set(_axis_names(eqn))
        if pname == "axis_index":
            out_taint = in_taint | (axes & manual_axes)
        elif pname in _INVARIANT_MAKERS and axes:
            out_taint = in_taint - axes
        elif pname == "scan":
            out_taint = _taint_scan(eqn, read, manual_axes)
            for v, t in zip(eqn.outvars, out_taint):
                env[v] = t
            continue
        elif pname == "while":
            outs = _taint_while(eqn, read, manual_axes)
            for v, t in zip(eqn.outvars, outs):
                env[v] = t
            continue
        elif pname == "cond":
            branch_outs = [
                _taint_jaxpr(b.jaxpr, [read(v) for v in eqn.invars[1:]],
                             manual_axes)
                for b in eqn.params["branches"]]
            pred_taint = read(eqn.invars[0])
            for v, ts in zip(eqn.outvars, zip(*branch_outs)):
                env[v] = frozenset().union(pred_taint, *ts)
            continue
        elif pname in ("pjit", "closed_call", "core_call", "remat_call",
                       "custom_jvp_call", "custom_vjp_call", "checkpoint",
                       "remat"):
            inner = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params:
                    inner = eqn.params[key]
                    break
            if inner is not None:
                ij = inner.jaxpr if hasattr(inner, "jaxpr") else inner
                outs = _taint_jaxpr(ij, [read(v) for v in eqn.invars],
                                    manual_axes)
                for v, t in zip(eqn.outvars, outs):
                    env[v] = t
                continue
            out_taint = in_taint
        else:
            out_taint = in_taint
        for v in eqn.outvars:
            env[v] = out_taint
    return [read(v) for v in jaxpr.outvars]


def _taint_while(eqn, read, manual_axes):
    """Fixpoint taint for a while_loop: recurse into the body (taint can
    be INTRODUCED inside it — axis_index in the body escapes a
    pass-through analysis), grow carry taints until stable, and if the
    COND is shard-varying the trip count diverges, tainting every carry."""
    cond_j = eqn.params["cond_jaxpr"].jaxpr
    body_j = eqn.params["body_jaxpr"].jaxpr
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cconsts = [read(v) for v in eqn.invars[:cn]]
    bconsts = [read(v) for v in eqn.invars[cn:cn + bn]]
    carry = [read(v) for v in eqn.invars[cn + bn:]]
    for _ in range(len(carry) + 2):
        outs = _taint_jaxpr(body_j, bconsts + carry, manual_axes)
        new_carry = [c | o for c, o in zip(carry, outs)]
        if new_carry == carry:
            break
        carry = new_carry
    cond_out = _taint_jaxpr(cond_j, cconsts + carry, manual_axes)
    if cond_out and cond_out[0]:
        carry = [c | cond_out[0] for c in carry]
    return carry


def _taint_scan(eqn, read, manual_axes):
    """Fixpoint taint for a scan: carry taints grow until stable."""
    body = eqn.params["jaxpr"].jaxpr
    nc, ncar = eqn.params["num_consts"], eqn.params["num_carry"]
    consts = [read(v) for v in eqn.invars[:nc]]
    carry = [read(v) for v in eqn.invars[nc:nc + ncar]]
    xs = [read(v) for v in eqn.invars[nc + ncar:]]
    for _ in range(ncar + 2):
        outs = _taint_jaxpr(body, consts + carry + xs, manual_axes)
        new_carry = [c | o for c, o in zip(carry, outs[:ncar])]
        if new_carry == carry:
            break
        carry = new_carry
    outs = _taint_jaxpr(body, consts + carry + xs, manual_axes)
    return [c | o for c, o in zip(carry, outs[:ncar])] + outs[ncar:]


def check_collectives(prog: TracedProgram) -> List[Finding]:
    err = _trace_failure(prog)
    if err is not None:
        msg = str(err)
        if isinstance(err, NameError) or "axis name" in msg \
                or "unbound" in msg:
            return [Finding(
                "GL003", JAXPR_PATH, 0,
                f"tracing aborts binding a collective axis: {msg} — a "
                "psum/ppermute/all_gather names an axis no enclosing "
                "mesh defines", context=prog.name)]
        return []
    findings = []
    for eqn, _ in _walk_eqns(_closed(prog.traced()).jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params["mesh"]
        mesh_axes = set(getattr(mesh, "axis_names", ()))
        manual = mesh_axes - set(eqn.params.get("auto", frozenset()))
        body = eqn.params["jaxpr"]
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        # (a) axis existence + (b) ppermute permutation validity
        for inner, _ in _walk_eqns(body):
            for ax in _axis_names(inner):
                if ax not in manual:
                    findings.append(Finding(
                        "GL003", JAXPR_PATH, 0,
                        f"`{inner.primitive.name}` names axis '{ax}' "
                        f"which is not manual on the enclosing shard_map "
                        f"mesh (manual axes: {sorted(manual)})",
                        context=prog.name))
            if inner.primitive.name == "ppermute":
                perm = list(inner.params.get("perm", ()))
                srcs = [s for s, _ in perm]
                dsts = [d for _, d in perm]
                if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                    findings.append(Finding(
                        "GL003", JAXPR_PATH, 0,
                        f"ppermute perm {perm} repeats a source or "
                        "target shard — not a permutation (data is "
                        "dropped or double-delivered)", context=prog.name))
                elif set(srcs) != set(dsts):
                    findings.append(Finding(
                        "GL003", JAXPR_PATH, 0,
                        f"ppermute perm {perm} has senders and receivers "
                        "that are not the same shard set — a ring "
                        "exchange built from this loses chunks",
                        context=prog.name))
        # (c) replicated-declared outputs must be replica-invariant
        in_taints = [frozenset(ax for axes_ in names.values() for ax in axes_)
                     & manual
                     for names in eqn.params["in_names"]]
        out_taints = _taint_jaxpr(body, in_taints, manual)
        for i, (names, taint) in enumerate(
                zip(eqn.params["out_names"], out_taints)):
            declared = {ax for axes_ in names.values() for ax in axes_}
            leaked = taint - declared
            if not names and leaked:
                findings.append(Finding(
                    "GL003", JAXPR_PATH, 0,
                    f"shard_map output {i} is declared REPLICATED but is "
                    f"shard-varying over {sorted(leaked)} by dataflow "
                    "(derives from a sharded input or axis_index with no "
                    "collective reduction in between) — with "
                    "check_rep=False this silently returns shard 0's "
                    "value", context=prog.name))
    return findings


# ---------------------------------------------------------------------------
# GL004 — RetraceBudget
# ---------------------------------------------------------------------------

def check_retrace(prog: TracedProgram) -> List[Finding]:
    if prog.retrace is None or _trace_failure(prog) is not None:
        return []
    try:
        first = str(_closed(prog.traced()))       # cached first trace
        second = str(_closed(prog.retrace()))     # fresh build + trace
        if first != second:
            # jax's pretty printer hoists a pjit sub-jaxpr (jnp.where,
            # floor_divide, ...) into a shared ``let _whereN = .. in``
            # binding only when its call sites reuse the SAME cached
            # jaxpr object, and whether they do depends on global
            # tracing-cache LRU state left behind by whatever else the
            # registry traced in between — so two semantically identical
            # traces can print differently on cache warmth alone.
            # Confirm on a level playing field: two fresh traces, each
            # from a cold tracing cache. Real offenders (counters,
            # dict/set order, wall-clock constants) still diverge
            # cold-vs-cold; printer-sharing artifacts do not.
            import jax
            jax.clear_caches()
            first = str(_closed(prog.retrace()))
            jax.clear_caches()
            second = str(_closed(prog.retrace()))
    except Exception as e:               # noqa: BLE001
        return [Finding(
            "GL004", JAXPR_PATH, 0,
            f"re-trace failed ({type(e).__name__}: {e}) — the entry "
            "point cannot be traced reproducibly", context=prog.name)]
    if first == second:
        return []
    diff_at = next((i for i, (a, b) in enumerate(
        zip(first.splitlines(), second.splitlines())) if a != b), None)
    detail = ("lengths differ" if diff_at is None
              else f"first divergence at jaxpr line {diff_at}")
    return [Finding(
        "GL004", JAXPR_PATH, 0,
        "two traces with identical bucket-compatible shapes produced "
        f"DIFFERENT jaxprs ({detail}): the jit cache key cannot be "
        "stable, so production pays a retrace per call — trace-time "
        "state (counters, dict/set iteration order, fresh closures) is "
        "leaking into the program", context=prog.name)]


ALL_JAXPR_CHECKS = (check_transfer, check_donation, check_collectives,
                    check_retrace)


def check_variant_program(prog: TracedProgram) -> List[Finding]:
    """GL001/GL002 (+ loud GL000) for the cost registry's non-default
    collective lowerings: GL003's taint pass cannot prove the ppermute
    ring replica-invariant (ring algebra, not local dataflow) and GL004
    is already pinned by the exact twin of the same entry point, so the
    variant twins run the transfer/donation checks only."""
    out = check_transfer(prog) + check_donation(prog)
    return _with_gl000(prog, out)


def check_program(prog: TracedProgram) -> List[Finding]:
    out: List[Finding] = []
    for check in ALL_JAXPR_CHECKS:
        out.extend(check(prog))
    return _with_gl000(prog, out)


def _with_gl000(prog: TracedProgram, out: List[Finding]) -> List[Finding]:
    err = _trace_failure(prog)
    if err is not None and not out:
        # the trace died for a reason no rule classifies (signature drift,
        # bad registry shapes, ...): a silent [] here would report "clean"
        # for a program that was never analyzed — fail loud; the innermost
        # repo traceback frame makes the abort debuggable from JSON output
        out.append(Finding(
            "GL000", JAXPR_PATH, 0,
            f"tracing failed at {failure_frame(err)} with {err!r} — the "
            "jaxpr checks (GL001-GL004) did not run for this program",
            context=prog.name))
    return out
