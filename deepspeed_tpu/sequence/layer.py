"""DeepSpeed-Ulysses sequence parallelism.

Analog of ``deepspeed/sequence/layer.py:145`` (DistributedAttention) and
``single_all_to_all:41`` / ``_SeqAllToAll:90``. The reference scatters heads /
gathers sequence with an explicit all-to-all autograd op before local
attention, and inverts it after. On TPU the same exchange is expressed two
ways, both provided:

- declarative (default): sharding constraints around the local attention
  (``ops/attention.py``) — XLA lowers the constraint flip seq-sharded →
  head-sharded to exactly one all-to-all over the ``seq`` ICI axis;
- explicit: :func:`seq_all_to_all` inside ``shard_map`` for code that wants
  the reference's manual op (and for the comm benchmark suite).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import groups


def seq_all_to_all(x, axis_name: str = "seq", scatter_idx: int = 2, gather_idx: int = 1):
    """All-to-all inside shard_map: scatter dim ``scatter_idx`` (heads),
    gather dim ``gather_idx`` (sequence). Analog of ``single_all_to_all:41``."""
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_idx,
                              concat_axis=gather_idx, tiled=True)


class DistributedAttention:
    """Wraps a local attention callable with the Ulysses exchange.

    ``local_attn(q, k, v, *args, **kwargs) -> out`` sees full-sequence,
    head-sharded tensors; inputs/outputs at the boundary are seq-sharded.
    API mirror of reference ``DistributedAttention(local_attn, sp_group,
    scatter_idx, gather_idx)``.
    """

    def __init__(self, local_attention: Callable, sequence_process_group=None,
                 scatter_idx: int = 2, gather_idx: int = 1,
                 sp_stream=None):
        self.local_attn = local_attention
        self.spg = sequence_process_group or ("seq",)
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query, key, value, *args, **kwargs):
        mesh = groups.get_mesh()
        axis = self.spg[0] if isinstance(self.spg, (tuple, list)) else self.spg
        if mesh.shape.get(axis, 1) <= 1:
            return self.local_attn(query, key, value, *args, **kwargs)

        batch_axes = tuple(a for a in groups.BATCH_AXES if mesh.shape.get(a, 1) > 1) or None
        seq_spec = P(batch_axes, axis, None, None)     # (B, S/sp, H, D)
        head_spec = P(batch_axes, None, axis, None)    # (B, S, H/sp, D)

        def constrain(x, spec):
            return jax.lax.with_sharding_constraint(x, jax.NamedSharding(mesh, spec))

        q = constrain(query, head_spec)
        k = constrain(key, head_spec)
        v = constrain(value, head_spec)
        out = self.local_attn(q, k, v, *args, **kwargs)
        return constrain(out, seq_spec)
