"""Sequence-parallel cross entropy.

Analog of ``deepspeed/sequence/cross_entropy.py:59``
(vocab_sequence_parallel_cross_entropy): with the sequence dim sharded, each
rank computes CE on its local tokens; the mean reduces over the seq axis.
Under jit with seq-sharded logits XLA produces this schedule from the plain
expression, so the explicit shard_map variant exists for parity and for use
inside manual regions.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import groups


def vocab_sequence_parallel_cross_entropy(logits, labels, axis_name: str = "seq"):
    """logits: (B, S_local, V) local shard inside shard_map; labels (B, S_local).

    Returns per-rank mean CE psum-averaged over the seq axis.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    local = jnp.mean(nll)
    return jax.lax.pmean(local, axis_name)


def sequence_parallel_cross_entropy(logits, labels, axis_name: str = "seq"):
    """Eager/jit helper over globally-shaped (seq-sharded) arrays."""
    mesh = groups.get_mesh()
    if mesh.shape.get(axis_name, 1) <= 1:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)
    batch_axes = tuple(a for a in groups.BATCH_AXES if mesh.shape.get(a, 1) > 1) or None
    lspec = P(batch_axes, axis_name, None)
    yspec = P(batch_axes, axis_name)
    all_axes = (axis_name,) + (batch_axes or ())
    fn = jax.shard_map(
        lambda lg, lb: vocab_sequence_parallel_cross_entropy(lg, lb, all_axes),
        mesh=mesh, in_specs=(lspec, yspec), out_specs=P(),
        axis_names={axis_name} | (set(batch_axes) if batch_axes else set()),
        check_vma=True)
    return fn(logits, labels)
