"""Ring attention: context parallelism over the ``seq`` mesh axis.

The reference has no ring attention (SURVEY.md §2.3 marks CP absent; Ulysses
is its long-sequence answer), but the TPU torus makes ring CP the idiomatic
long-context mechanism: each rank holds a sequence shard of Q/K/V, K/V blocks
rotate around the ring via ``ppermute`` while flash-style online-softmax
statistics (m, l, acc) merge partial results — peak memory stays O(S/n) per
chip and comm rides neighbor ICI links only.

Feature parity with the flash kernel (round-3): masking is computed from
GLOBAL positions per ring step, so sliding windows, ALiBi slopes, and
packed-sequence segment ids (which rotate around the ring with their KV
shard) all compose with the causal ring — long-context packed pretraining
can choose ring vs Ulysses on merit rather than on feature support.

Memory (round-4): each ring step computes its scores in 512-query chunks
(flash-style, expressed as a ``lax.scan`` XLA fuses per chunk), so the peak
fp32 intermediate is (B, H, 512, S/n) rather than (B, H, S/n, S/n), and
GQA contracts grouped einsums against the raw KV heads — K/V are never
``repeat``-materialized. At 64k tokens on 8 ranks that is ~16x less
attention scratch per step than the round-3 form.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import groups
from .ring_flash import _vary, ring_flash_body, ring_flash_supported

NEG_INF = -1e30

_RING_CACHE = {}
# entries key on the live mesh; drop them when the mesh is rebuilt
groups.register_reset_hook(_RING_CACHE.clear)


def _block_attend(q, k, v, scale, q_pos, k_pos, window, seg_q, seg_k,
                  slopes, chunk=512):
    """Partial (unnormalized) attention of local q against one kv block,
    computed in QUERY CHUNKS: the (B, H, Cq, Sk) fp32 scores are the peak
    intermediate, not (B, H, Sq, Sk) — at real long-context shard sizes the
    full block would bound memory (round-3 review). GQA contracts against
    the raw (B, Sk, KVH, D) K/V via a grouped einsum — kv heads are never
    repeated.

    Returns (m, l, o_partial): (B, H, Sq), (B, H, Sq), (B, Sq, H, D) fp32.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kvh = k.shape[2]
    g = h // kvh
    cq = min(chunk, sq)
    if sq % cq:
        cq = sq   # odd shard sizes: one chunk (tests; real shards are 2^k)
    nq = sq // cq
    q5 = q.reshape(b, nq, cq, kvh, g, d).transpose(1, 0, 2, 3, 4, 5)
    qpos_c = q_pos.reshape(nq, cq)
    segq_c = (None if seg_q is None
              else seg_q.reshape(b, nq, cq).transpose(1, 0, 2))

    def one(_, xs):
        if seg_q is not None:
            qc, qp, sg = xs
        else:
            (qc, qp), sg = xs, None
        # (B, Cq, KVH, G, D) x (B, Sk, KVH, D) -> (B, KVH, G, Cq, Sk)
        s = jnp.einsum("bcngd,bknd->bngck", qc, k,
                       preferred_element_type=jnp.float32) * scale
        rel = qp[:, None] - k_pos[None, :]                    # (Cq, Sk)
        if slopes is not None:
            s = s + (slopes.reshape(kvh, g)[None, :, :, None, None]
                     * (-rel).astype(jnp.float32)[None, None, None])
        mask = rel >= 0                                       # causal
        if window is not None:
            from ..ops.attention import window_mask
            mask = mask & window_mask(qp[:, None], k_pos[None, :], window)
        mask = mask[None, None, None]                         # (1,1,1,Cq,Sk)
        if sg is not None:
            mask = mask & (sg[:, None, None, :, None]
                           == seg_k[:, None, None, None, :])
        s = jnp.where(mask, s, NEG_INF)
        m = jnp.max(s, axis=-1)                               # (B, KVH, G, Cq)
        p = jnp.exp(s - m[..., None])
        p = jnp.where(mask, p, 0.0)       # kill exp(NEG_INF - NEG_INF)
        l = jnp.sum(p, axis=-1)
        o = jnp.einsum("bngck,bknd->bcngd", p.astype(v.dtype), v)
        return None, (m, l, o.astype(jnp.float32))

    xs = (q5, qpos_c, segq_c) if seg_q is not None else (q5, qpos_c)
    _, (m, l, o) = jax.lax.scan(one, None, xs)
    # (nq, B, KVH, G, Cq) -> (B, H, Sq);  (nq, B, Cq, KVH, G, D) -> (B, Sq, H, D)
    m = m.transpose(1, 2, 3, 0, 4).reshape(b, h, sq)
    l = l.transpose(1, 2, 3, 0, 4).reshape(b, h, sq)
    o = o.transpose(1, 0, 2, 3, 4, 5).reshape(b, sq, h, d)
    return m, l, o


def _ring_body(q, k, v, seg, axis_name, scale, window, slopes, vary_axes=None):
    """Runs on one rank inside shard_map: q/k/v (and segment ids) are local
    seq shards; equal shard sizes give global positions rank*shard + i."""
    n = jax.lax.axis_size(axis_name)
    p_idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q_pos = p_idx * sq + jnp.arange(sq)                       # (Sq,) global

    def step(i, carry):
        m_acc, l_acc, o_acc, kv = carry
        k_blk, v_blk, kseg_blk = kv
        src = (p_idx - i) % n        # rank that produced this kv block
        k_pos = src * sk + jnp.arange(sk)
        m_b, l_b, o_b = _block_attend(q, k_blk, v_blk, scale, q_pos, k_pos,
                                      window, seg, kseg_blk, slopes)
        m_new = jnp.maximum(m_acc, m_b)
        a_old = jnp.exp(m_acc - m_new)
        a_new = jnp.exp(m_b - m_new)
        l_new = l_acc * a_old + l_b * a_new
        o_new = (o_acc * jnp.moveaxis(a_old, 1, -1)[..., None] +
                 o_b * jnp.moveaxis(a_new, 1, -1)[..., None])
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv_next = (jax.lax.ppermute(k_blk, axis_name, perm),
                   jax.lax.ppermute(v_blk, axis_name, perm),
                   None if kseg_blk is None else
                   jax.lax.ppermute(kseg_blk, axis_name, perm))
        return m_new, l_new, o_new, kv_next

    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    m0 = _vary(jnp.full((b, h, sq), NEG_INF, jnp.float32), axes)
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32), axes)
    o0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32), axes)
    step = jax.checkpoint(step, static_argnums=())
    m, l, o, _ = jax.lax.fori_loop(0, n, step, (m0, l0, o0, (k, v, seg)))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / jnp.moveaxis(l_safe, 1, -1)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str = "seq", scale=None,
                   window=None, alibi_slopes=None, segment_ids=None):
    """Causal ring attention. q/k/v: (B, S, H|KVH, D) GLOBAL logical shapes,
    seq-sharded over ``axis_name``. Returns (B, S, H, D) seq-sharded.

    window: sliding-window width (static or traced; <= 0 = global);
    alibi_slopes: (H,) per-head slopes; segment_ids: (B, S) int — packed
    documents attend within their own segment only (the key-side ids rotate
    around the ring with their shard).
    """
    mesh = groups.get_mesh()
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    batch_axes = tuple(a for a in groups.BATCH_AXES if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch_axes, axis_name, None, None)
    seg_spec = P(batch_axes, axis_name)

    slopes = None
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)

    vary_axes = (axis_name,) + (batch_axes or ())
    has_seg = segment_ids is not None

    # Pallas ring-flash eligibility (static): scores never leave VMEM —
    # the einsum body (fp32 (B, H, Cq, S/n) HBM chunks) stays as the
    # fallback for odd shard shapes / traced windows / non-TPU-unfriendly
    # head dims, and as the parity reference.
    n_ring = mesh.shape[axis_name]
    sq_local = q.shape[1] // max(n_ring, 1)
    win_static = (None if window is None or
                  (isinstance(window, int) and window <= 0) else window)
    # Mosaic cannot lower under a PARTIAL-manual mesh (mixed Manual/Auto
    # axes): the flash ring goes full-manual, which is semantics-preserving
    # only when every axis outside {ring, batch} is trivial — tensor-sharded
    # heads etc. keep the einsum body (XLA partitions around it).
    manual_axes = {axis_name} | set(batch_axes or ())
    full_manual_ok = all(size == 1 for a, size in mesh.shape.items()
                         if a not in manual_axes)
    use_flash = (os.environ.get("DS_TPU_RING_FLASH", "1") != "0"
                 and full_manual_ok
                 and q.shape[1] % max(n_ring, 1) == 0
                 and ring_flash_supported(sq_local, sq_local, d, win_static))

    def build():
        if use_flash:
            body = functools.partial(ring_flash_body, axis_name=axis_name,
                                     scale=scale, window=win_static,
                                     slopes=alibi_slopes,
                                     vary_axes=vary_axes)
        else:
            body = functools.partial(_ring_body, axis_name=axis_name,
                                     scale=scale, window=window,
                                     slopes=slopes, vary_axes=vary_axes)
        fn = jax.shard_map(
            body if has_seg else functools.partial(body, seg=None),
            mesh=mesh,
            in_specs=(spec, spec, spec) + ((seg_spec,) if has_seg else ()),
            out_specs=spec,
            # flash: ALL axes manual (Mosaic rejects partial-manual);
            # eligibility guarantees the extra axes are trivial
            axis_names=(set(mesh.shape) if use_flash else
                        {axis_name} | (set(batch_axes) if batch_axes else set())),
            # interpret-mode pallas_call strips vma from ref reads, so the
            # kernel path cannot satisfy the strict vma type system; the
            # einsum body keeps it on
            check_vma=not use_flash)
        # jit: the chunked scan inside the manual region cannot evaluate
        # eagerly (free when this call is itself inside an outer jit)
        return jax.jit(fn)

    # cache the jitted ring per static config: jax.jit keys on the callable
    # object, and rebuilding it per call would recompile every EAGER
    # invocation. Unhashable statics (traced window — only possible under
    # an outer jit, where nesting makes the rebuild free) skip the cache.
    try:
        key = (mesh, axis_name, float(scale),
               window if isinstance(window, (int, type(None))) else None,
               None if alibi_slopes is None
               else tuple(float(x) for x in jnp.asarray(alibi_slopes)),
               has_seg, use_flash)
        hashable = isinstance(window, (int, type(None)))
    except Exception:
        hashable = False
    if hashable:
        fn = _RING_CACHE.get(key)
        if fn is None:
            fn = _RING_CACHE[key] = build()
    else:
        fn = build()
    return fn(q, k, v, *((segment_ids,) if has_seg else ()))
