"""Ring attention: context parallelism over the ``seq`` mesh axis.

The reference has no ring attention (SURVEY.md §2.3 marks CP absent; Ulysses
is its long-sequence answer), but the TPU torus makes ring CP the idiomatic
long-context mechanism: each rank holds a sequence shard of Q/K/V, K/V blocks
rotate around the ring via ``ppermute`` while flash-style online-softmax
statistics (m, l, acc) merge partial results — peak memory stays O(S/n) per
chip and comm rides neighbor ICI links only.

Feature parity with the flash kernel (round-3): masking is computed from
GLOBAL positions per ring step, so sliding windows, ALiBi slopes, and
packed-sequence segment ids (which rotate around the ring with their KV
shard) all compose with the causal ring — long-context packed pretraining
can choose ring vs Ulysses on merit rather than on feature support.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import groups

NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask, bias=None):
    """Partial (unnormalized) attention of local q against one kv block.

    mask: (B|1, 1, Sq, Sk) bool visibility; bias: optional additive
    (1, H, Sq, Sk) term (ALiBi). Returns (m, l, o_partial).
    q: (B, Sq, H, D); k/v: (B, Sk, KVH, D).
    """
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # (B, H, Sq)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)                               # kill exp(NEG_INF - NEG_INF)
    l = jnp.sum(p, axis=-1)                                   # (B, H, Sq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)   # (B, Sq, H, D)
    return m, l, o.astype(jnp.float32)


def _ring_body(q, k, v, seg, axis_name, scale, window, slopes, vary_axes=None):
    """Runs on one rank inside shard_map: q/k/v (and segment ids) are local
    seq shards; equal shard sizes give global positions rank*shard + i."""
    n = jax.lax.axis_size(axis_name)
    p_idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q_pos = p_idx * sq + jnp.arange(sq)                       # (Sq,) global

    def step(i, carry):
        m_acc, l_acc, o_acc, kv = carry
        k_blk, v_blk, kseg_blk = kv
        src = (p_idx - i) % n        # rank that produced this kv block
        k_pos = src * sk + jnp.arange(sk)
        rel = q_pos[:, None] - k_pos[None, :]                 # (Sq, Sk)
        mask2 = rel >= 0                                      # causal
        if window is not None:
            from ..ops.attention import window_mask
            mask2 = mask2 & window_mask(q_pos[:, None], k_pos[None, :], window)
        mask = mask2[None, None]                              # (1,1,Sq,Sk)
        if kseg_blk is not None:
            mask = mask & (seg[:, None, :, None] == kseg_blk[:, None, None, :])
        bias = None
        if slopes is not None:
            bias = (slopes[:, None, None] * (-rel).astype(jnp.float32))[None]
        m_b, l_b, o_b = _block_attend(q, k_blk, v_blk, scale, mask, bias)
        m_new = jnp.maximum(m_acc, m_b)
        a_old = jnp.exp(m_acc - m_new)
        a_new = jnp.exp(m_b - m_new)
        l_new = l_acc * a_old + l_b * a_new
        o_new = (o_acc * jnp.moveaxis(a_old, 1, -1)[..., None] +
                 o_b * jnp.moveaxis(a_new, 1, -1)[..., None])
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv_next = (jax.lax.ppermute(k_blk, axis_name, perm),
                   jax.lax.ppermute(v_blk, axis_name, perm),
                   None if kseg_blk is None else
                   jax.lax.ppermute(kseg_blk, axis_name, perm))
        return m_new, l_new, o_new, kv_next

    axes = tuple(vary_axes) if vary_axes else (axis_name,)

    def _vary(x):
        if hasattr(jax.lax, "pcast"):
            return jax.lax.pcast(x, axes, to="varying")
        return jax.lax.pvary(x, axes)

    m0 = _vary(jnp.full((b, h, sq), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32))
    o0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    step = jax.checkpoint(step, static_argnums=())
    m, l, o, _ = jax.lax.fori_loop(0, n, step, (m0, l0, o0, (k, v, seg)))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / jnp.moveaxis(l_safe, 1, -1)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str = "seq", scale=None,
                   window=None, alibi_slopes=None, segment_ids=None):
    """Causal ring attention. q/k/v: (B, S, H|KVH, D) GLOBAL logical shapes,
    seq-sharded over ``axis_name``. Returns (B, S, H, D) seq-sharded.

    window: sliding-window width (static or traced; <= 0 = global);
    alibi_slopes: (H,) per-head slopes; segment_ids: (B, S) int — packed
    documents attend within their own segment only (the key-side ids rotate
    around the ring with their shard).
    """
    mesh = groups.get_mesh()
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    batch_axes = tuple(a for a in groups.BATCH_AXES if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch_axes, axis_name, None, None)
    seg_spec = P(batch_axes, axis_name)

    slopes = None
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)

    vary_axes = (axis_name,) + (batch_axes or ())
    has_seg = segment_ids is not None
    body = functools.partial(_ring_body, axis_name=axis_name, scale=scale,
                             window=window, slopes=slopes,
                             vary_axes=vary_axes)
    fn = jax.shard_map(
        body if has_seg else functools.partial(body, seg=None),
        mesh=mesh,
        in_specs=(spec, spec, spec) + ((seg_spec,) if has_seg else ()),
        out_specs=spec,
        axis_names={axis_name} | (set(batch_axes) if batch_axes else set()),
        check_vma=True)
    return fn(q, k, v, *((segment_ids,) if has_seg else ()))
