"""Ring attention with a fused Pallas flash inner kernel.

SURVEY §7's planned design ("Pallas flash kernel with ppermute KV rotation"):
the einsum ring (``ring_attention.py``) materializes fp32 (B, H, Cq, S/n)
score chunks in HBM per ring step; here each ring step runs a flash
CONTINUATION kernel — the online-softmax carry (m, l, acc) threads through
``n`` kernel invocations while K/V blocks rotate around the ``seq`` axis via
``ppermute`` — so scores only ever exist as (block_q, block_k) VMEM tiles.

Masking is computed from GLOBAL positions (q_offset/k_offset ride in as
scalar-prefetch operands, traced per ring step), so causal, sliding-window,
ALiBi, and packed-segment masking compose exactly as in the einsum ring and
the local flash kernel (``ops/pallas/flash_attention.py``) — parity tests
assert all four against the einsum reference.

The backward is a second ring: dK/dV accumulators rotate WITH their K/V
blocks (each returns home after n steps having collected every rank's
contribution), dQ accumulates locally; both are computed by per-step Pallas
kernels using the saved forward lse — the FlashAttention-2 recomputation
scheme stretched around the ring. The reference has no CP at all
(``deepspeed/sequence/layer.py:145`` — Ulysses is its only long-sequence
mechanism); this kernel is the TPU-native extension.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _cdiv(a, b):
    return (a + b - 1) // b


def _global_q_ranges(rows_base, k_off, block_q, block_k, num_kv, window):
    """KV-block loop bounds for the q block starting at GLOBAL row
    ``rows_base`` against a kv shard starting at GLOBAL col ``k_off``
    (both traced): (kv_lo, full_lo, full_hi, kv_hi); [full_lo, full_hi) is
    mask-free. The global generalization of flash's ``_q_block_ranges`` —
    with rows_base/k_off of the local shard it reduces to the same bounds.
    """
    zero = jnp.int32(0)
    nkv = jnp.int32(num_kv)
    # causal: block j visible iff its first col <= the block's last row
    kv_hi = jnp.clip(_cdiv(rows_base + block_q - k_off, block_k), zero, nkv)
    # mask-free (causal) iff the block's last col < the block's first row
    n_full = jnp.clip((rows_base - k_off) // block_k, zero, nkv)
    if window is None:
        return zero, zero, n_full, kv_hi
    kv_lo = jnp.clip((rows_base - window + 1 - k_off) // block_k, zero, nkv)
    lo_full = _cdiv(rows_base + block_q - window - k_off, block_k)
    full_lo = jnp.clip(lo_full, kv_lo, kv_hi)
    full_hi = jnp.clip(n_full, full_lo, kv_hi)
    return kv_lo, full_lo, full_hi, kv_hi


def _ring_fwd_kernel(off_ref,                      # scalar prefetch (2,)
                     q_ref, k_ref, v_ref, slopes_ref, qseg_ref, kseg_ref,
                     m_in_ref, l_in_ref, acc_in_ref,
                     m_ref, l_ref, acc_ref, *,
                     alibi, segmented, window, block_q, block_k):
    qi = pl.program_id(2)
    q_off = off_ref[0]
    k_off = off_ref[1]
    q = q_ref[0, 0]                                     # (Bq, D)
    rows_base = q_off + qi * block_q
    num_kv = k_ref.shape[2] // block_k
    slope = slopes_ref[pl.program_id(1), 0] if alibi else None
    qseg = qseg_ref[0, 0, pl.ds(pl.multiple_of(qi * block_q, block_q),
                                block_q)] if segmented else None
    kv_lo, full_lo, full_hi, kv_hi = _global_q_ranges(
        rows_base, k_off, block_q, block_k, num_kv, window)
    if segmented:
        full_lo, full_hi = kv_lo, kv_lo      # every block needs the seg mask

    def make_body(masked):
        def body(j, carry):
            m, l, acc = carry
            k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
            v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if alibi or masked:
                rows = rows_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                cols = k_off + j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 1)
            if alibi:
                s = s + slope * (cols - rows).astype(jnp.float32)
            if masked:
                keep = rows >= cols
                if window is not None:
                    keep = keep & (rows - cols < window)
                if segmented:
                    kseg = kseg_ref[0, 0, pl.ds(j * block_k, block_k)]
                    keep = keep & (qseg[:, None] == kseg[None, :])
                s = jnp.where(keep, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, None])
            if masked:
                p = jnp.where(keep, p, 0.0)   # kill exp(NEG_INF - NEG_INF)
            l_new = l * alpha + jnp.sum(p, axis=1)
            acc_new = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    carry = (m_in_ref[0, 0, 0], l_in_ref[0, 0, 0], acc_in_ref[0, 0])
    carry = jax.lax.fori_loop(kv_lo, full_lo, make_body(True), carry)
    carry = jax.lax.fori_loop(full_lo, full_hi, make_body(False), carry)
    m, l, acc = jax.lax.fori_loop(full_hi, kv_hi, make_body(True), carry)
    m_ref[0, 0, 0] = m
    l_ref[0, 0, 0] = l
    acc_ref[0, 0] = acc


def _fwd_step(off, q, k, v, slopes, qseg, kseg, m, l, acc, *,
              alibi, segmented, window, block_q, block_k, vma):
    """One ring step: fold one rotating KV block into the carry.
    q: (B, H, Sq, D) pre-scaled; k/v: (B, KVH, Sk, D); m/l: (B, H, Sq) f32;
    acc: (B, H, Sq, D) f32; off: int32 (2,) = (q_offset, k_offset)."""
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    group = h // kvh
    grid = (b, h, sq // block_q)
    qmap = lambda bi, hi, qi, off_: (bi, hi, qi, 0)
    kvmap = lambda bi, hi, qi, off_: (bi, hi // group, 0, 0)
    mlmap = lambda bi, hi, qi, off_: (bi, hi, 0, qi)
    return pl.pallas_call(
        functools.partial(_ring_fwd_kernel, alibi=alibi, segmented=segmented,
                          window=window, block_q=block_q, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d), qmap),
                pl.BlockSpec((1, 1, sk, d), kvmap),
                pl.BlockSpec((1, 1, sk, d), kvmap),
                pl.BlockSpec((h, 128), lambda bi, hi, qi, off_: (0, 0)),
                pl.BlockSpec((1, 1, qseg.shape[2]),
                             lambda bi, hi, qi, off_: (bi, 0, 0)),
                pl.BlockSpec((1, 1, kseg.shape[2]),
                             lambda bi, hi, qi, off_: (bi, 0, 0)),
                pl.BlockSpec((1, 1, 1, block_q), mlmap),
                pl.BlockSpec((1, 1, 1, block_q), mlmap),
                pl.BlockSpec((1, 1, block_q, d), qmap),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, 1, block_q), mlmap),
                pl.BlockSpec((1, 1, 1, block_q), mlmap),
                pl.BlockSpec((1, 1, block_q, d), qmap),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32, vma=vma),
        ],
        input_output_aliases={7: 0, 8: 1, 9: 2},   # carry updated in place
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(off, q, k, v, slopes, qseg, kseg, m, l, acc)


def _ring_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    slopes_ref, qseg_ref, kseg_ref, dq_ref, *,
                    alibi, segmented, window, block_q, block_k):
    qi = pl.program_id(2)
    q_off = off_ref[0]
    k_off = off_ref[1]
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, 0]
    delta = delta_ref[0, 0, 0]
    rows_base = q_off + qi * block_q
    num_kv = k_ref.shape[2] // block_k
    slope = slopes_ref[pl.program_id(1), 0] if alibi else None
    qseg = qseg_ref[0, 0, pl.ds(pl.multiple_of(qi * block_q, block_q),
                                block_q)] if segmented else None
    kv_lo, full_lo, full_hi, kv_hi = _global_q_ranges(
        rows_base, k_off, block_q, block_k, num_kv, window)
    if segmented:
        full_lo, full_hi = kv_lo, kv_lo

    def make_body(masked):
        def body(j, dq):
            k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
            v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if alibi or masked:
                rows = rows_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                cols = k_off + j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 1)
            if alibi:
                s = s + slope * (cols - rows).astype(jnp.float32)
            if masked:
                keep = rows >= cols
                if window is not None:
                    keep = keep & (rows - cols < window)
                if segmented:
                    kseg = kseg_ref[0, 0, pl.ds(j * block_k, block_k)]
                    keep = keep & (qseg[:, None] == kseg[None, :])
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            if masked:
                p = jnp.where(keep, p, 0.0)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, None])).astype(k.dtype)
            return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                            preferred_element_type=jnp.float32)
        return body

    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(kv_lo, full_lo, make_body(True), dq)
    dq = jax.lax.fori_loop(full_lo, full_hi, make_body(False), dq)
    dq = jax.lax.fori_loop(full_hi, kv_hi, make_body(True), dq)
    dq_ref[0, 0] = dq


def _ring_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     slopes_ref, qseg_ref, kseg_ref, dk_ref, dv_ref, *,
                     alibi, segmented, window, block_q, block_k):
    ki = pl.program_id(2)
    q_off = off_ref[0]
    k_off = off_ref[1]
    k = k_ref[0, 0]                                      # (Bk, D)
    v = v_ref[0, 0]
    cols_base = k_off + ki * block_k
    num_q = q_ref.shape[2] // block_q
    slope = slopes_ref[pl.program_id(1), 0] if alibi else None
    kseg = kseg_ref[0, 0, pl.ds(pl.multiple_of(ki * block_k, block_k),
                                block_k)] if segmented else None
    # dual bounds in global coords: q blocks with last row >= first col
    zero = jnp.int32(0)
    nq = jnp.int32(num_q)
    q_lo = jnp.clip((cols_base - q_off) // block_q, zero, nq)
    # mask-free once the block's first row > the block's last col
    i_um = jnp.clip(_cdiv(cols_base + block_k - q_off, block_q), zero, nq)
    if window is not None:
        q_hi = jnp.clip(_cdiv(cols_base + block_k + window - q_off, block_q),
                        zero, nq)
        i_full_end = jnp.clip((cols_base + window - q_off) // block_q,
                              zero, nq)
    else:
        q_hi = nq
        i_full_end = nq

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
            do = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
            lse = lse_ref[0, 0, 0, pl.ds(i * block_q, block_q)]
            delta = delta_ref[0, 0, 0, pl.ds(i * block_q, block_q)]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if alibi or masked:
                rows = q_off + i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                cols = cols_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if alibi:
                s = s + slope * (cols - rows).astype(jnp.float32)
            if masked:
                keep = rows >= cols
                if window is not None:
                    keep = keep & (rows - cols < window)
                if segmented:
                    qseg = qseg_ref[0, 0, pl.ds(i * block_q, block_q)]
                    keep = keep & (qseg[:, None] == kseg[None, :])
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            if masked:
                p = jnp.where(keep, p, 0.0)
            dv_new = dv + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, None])).astype(q.dtype)
            dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)
            return dk_new, dv_new
        return body

    zeros = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    if segmented:
        m1_end = q_hi
        full_end = q_hi
    else:
        m1_end = jnp.clip(i_um, q_lo, q_hi)
        full_end = jnp.clip(i_full_end, m1_end, q_hi)
    dk, dv = jax.lax.fori_loop(q_lo, m1_end, make_body(True), (zeros, zeros))
    dk, dv = jax.lax.fori_loop(m1_end, full_end, make_body(False), (dk, dv))
    dk, dv = jax.lax.fori_loop(full_end, q_hi, make_body(True), (dk, dv))
    dk_ref[0, 0] = dk
    dv_ref[0, 0] = dv


def _bwd_step(off, q, k, v, do, lse, delta, slopes, qseg, kseg, *,
              alibi, segmented, window, block_q, block_k, vma):
    """Per-ring-step gradients: dq (B, H, Sq, D) f32, and this KV block's
    dk/dv (B, KVH, Sk, D) f32 (summed over the GQA group in-step so the
    rotating accumulator stays KVH-sized)."""
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    group = h // kvh
    common = dict(alibi=alibi, segmented=segmented, window=window,
                  block_q=block_q, block_k=block_k)
    kvmap = lambda bi, hi, qi, off_: (bi, hi // group, 0, 0)
    qmap = lambda bi, hi, qi, off_: (bi, hi, qi, 0)
    smap = lambda bi, hi, qi, off_: (0, 0)
    qsegmap = lambda bi, hi, qi, off_: (bi, 0, 0)
    lsemap = lambda bi, hi, qi, off_: (bi, hi, 0, qi)
    dq = pl.pallas_call(
        functools.partial(_ring_dq_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h, sq // block_q),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d), qmap),
                pl.BlockSpec((1, 1, sk, d), kvmap),
                pl.BlockSpec((1, 1, sk, d), kvmap),
                pl.BlockSpec((1, 1, block_q, d), qmap),
                pl.BlockSpec((1, 1, 1, block_q), lsemap),
                pl.BlockSpec((1, 1, 1, block_q), lsemap),
                pl.BlockSpec((h, 128), smap),
                pl.BlockSpec((1, 1, qseg.shape[2]), qsegmap),
                pl.BlockSpec((1, 1, kseg.shape[2]), qsegmap),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d), qmap),
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32, vma=vma),
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(off, q, k, v, do, lse, delta, slopes, qseg, kseg)

    fullq = lambda bi, hi, ki_, off_: (bi, hi, 0, 0)
    kmap = lambda bi, hi, ki_, off_: (bi, hi // group, ki_, 0)
    lmap = lambda bi, hi, ki_, off_: (bi, hi, 0, 0)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_ring_dkv_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h, sk // block_k),
            in_specs=[
                pl.BlockSpec((1, 1, sq, d), fullq),
                pl.BlockSpec((1, 1, block_k, d), kmap),
                pl.BlockSpec((1, 1, block_k, d), kmap),
                pl.BlockSpec((1, 1, sq, d), fullq),
                pl.BlockSpec((1, 1, 1, sq), lmap),
                pl.BlockSpec((1, 1, 1, sq), lmap),
                pl.BlockSpec((h, 128), lambda bi, hi, ki_, off_: (0, 0)),
                pl.BlockSpec((1, 1, qseg.shape[2]),
                             lambda bi, hi, ki_, off_: (bi, 0, 0)),
                pl.BlockSpec((1, 1, kseg.shape[2]),
                             lambda bi, hi, ki_, off_: (bi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_k, d),
                             lambda bi, hi, ki_, off_: (bi, hi, ki_, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda bi, hi, ki_, off_: (bi, hi, ki_, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32, vma=vma),
        ],
        interpret=_interpret(),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(off, q, k, v, do, lse, delta, slopes, qseg, kseg)
    if group > 1:
        dk = dk_h.reshape(b, kvh, group, sk, d).sum(axis=2)
        dv = dv_h.reshape(b, kvh, group, sk, d).sum(axis=2)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv


def _rotate(axis_name, *xs):
    n = jax.lax.axis_size(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    return tuple(None if x is None else jax.lax.ppermute(x, axis_name, perm)
                 for x in xs)


def _vary(x, axes):
    """Mark device-constant arrays as axis-varying so loop carries and
    kernel operands type-check under shard_map's check_vma."""
    if not axes:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axes), to="varying")
    return jax.lax.pvary(x, tuple(axes))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _ring_flash_local(q, k, v, seg, slopes, axis_name, window, use_alibi,
                      block_q, block_k, vary_axes):
    out, _ = _ring_fwd_local(q, k, v, seg, slopes, axis_name, window,
                             use_alibi, block_q, block_k, vary_axes)
    return out


def _ring_fwd_local(q, k, v, seg, slopes, axis_name, window, use_alibi,
                    block_q, block_k, vary_axes):
    """Runs inside shard_map. q: (B, Sq, H, D) PRE-SCALED local shard;
    k/v: (B, Sk, KVH, D); seg: (B, Sq) int32 or None (static flag);
    slopes: (H, 128) f32 (zeros when ``use_alibi`` is False — slopes are
    non-differentiable constants, as in the local flash kernel).
    Returns (o (B, Sq, H, D), lse (B, H, Sq))."""
    n = jax.lax.axis_size(axis_name)
    p_idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    segmented = seg is not None
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    qseg = (seg[:, None, :] if segmented
            else _vary(jnp.zeros((b, 1, 128), jnp.int32), vary_axes))
    slopes = _vary(slopes, vary_axes)
    m0 = _vary(jnp.full((b, h, 1, sq), NEG_INF, jnp.float32), vary_axes)
    l0 = _vary(jnp.zeros((b, h, 1, sq), jnp.float32), vary_axes)
    acc0 = _vary(jnp.zeros((b, h, sq, d), jnp.float32), vary_axes)

    def step(i, carry):
        m, l, acc, kv = carry
        k_blk, v_blk, kseg_blk = kv
        src = (p_idx - i) % n
        off = jnp.stack([p_idx * sq, src * sk]).astype(jnp.int32)
        m, l, acc = _fwd_step(
            off, qt, k_blk, v_blk, slopes, qseg,
            kseg_blk if segmented else qseg,
            m, l, acc, alibi=use_alibi, segmented=segmented,
            window=window, block_q=block_q, block_k=block_k,
            vma=frozenset(vary_axes))
        kv_next = _rotate(axis_name, k_blk, v_blk, kseg_blk)
        return m, l, acc, kv_next

    kseg0 = seg[:, None, :] if segmented else None
    m, l, acc, _ = jax.lax.fori_loop(
        0, n, step, (m0, l0, acc0, (kt, vt, kseg0)))
    m, l = m[:, :, 0, :], l[:, :, 0, :]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l_safe[..., None]).astype(q.dtype).transpose(0, 2, 1, 3)
    lse = m + jnp.log(l_safe)
    return o, lse


def _ring_flash_fwd_rule(q, k, v, seg, slopes, axis_name, window, use_alibi,
                         block_q, block_k, vary_axes):
    out, lse = _ring_fwd_local(q, k, v, seg, slopes, axis_name, window,
                               use_alibi, block_q, block_k, vary_axes)
    return out, (q, k, v, seg, slopes, out, lse)


def _ring_flash_bwd_rule(axis_name, window, use_alibi, block_q, block_k,
                         vary_axes, residuals, g):
    q, k, v, seg, slopes, out, lse = residuals
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kvh = k.shape[2]
    segmented = seg is not None
    n = jax.lax.axis_size(axis_name)
    p_idx = jax.lax.axis_index(axis_name)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = g.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)
    qseg = (seg[:, None, :] if segmented
            else _vary(jnp.zeros((b, 1, 128), jnp.int32), vary_axes))
    slopes = _vary(slopes, vary_axes)
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1)[:, :, None, :]              # (B, H, 1, Sq)
    lse4 = lse[:, :, None, :]

    def step(i, carry):
        dq, kvg = carry
        k_blk, v_blk, kseg_blk, dk_acc, dv_acc = kvg
        src = (p_idx - i) % n
        off = jnp.stack([p_idx * sq, src * sk]).astype(jnp.int32)
        dq_s, dk_s, dv_s = _bwd_step(
            off, qt, k_blk, v_blk, dot, lse4, delta, slopes, qseg,
            kseg_blk if segmented else qseg,
            alibi=use_alibi, segmented=segmented, window=window,
            block_q=block_q, block_k=block_k, vma=frozenset(vary_axes))
        # accumulate BEFORE rotating: this block's grad accumulator collects
        # each rank's contribution as it travels, arriving home after n steps
        kvg_next = _rotate(axis_name, k_blk, v_blk, kseg_blk,
                           dk_acc + dk_s, dv_acc + dv_s)
        return dq + dq_s, kvg_next

    dk0 = _vary(jnp.zeros((b, kvh, sk, d), jnp.float32), vary_axes)
    dq0 = _vary(jnp.zeros((b, h, sq, d), jnp.float32), vary_axes)
    dq, (_, _, _, dk, dv) = jax.lax.fori_loop(
        0, n, step, (dq0, (kt, vt, seg[:, None, :] if segmented else None,
                           dk0, dk0)))
    dq = dq.transpose(0, 2, 1, 3).astype(q.dtype)
    dk = dk.transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv.transpose(0, 2, 1, 3).astype(v.dtype)
    dseg = None if seg is None else np.zeros(seg.shape, jax.dtypes.float0)
    return dq, dk, dv, dseg, jnp.zeros_like(slopes)


_ring_flash_local.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_flash_body(q, k, v, seg=None, *, axis_name, scale, window,
                    slopes, vary_axes=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """shard_map body: ring attention with the Pallas flash inner kernel.
    Same contract as ``ring_attention._ring_body`` (local (B, S/n, H|KVH, D)
    shards in, (B, S/n, H, D) out)."""
    b, sq, h, d = q.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, k.shape[1])
    use_alibi = slopes is not None
    slopes_arr = (jnp.broadcast_to(
        jnp.asarray(slopes, jnp.float32)[:, None], (h, 128))
        if use_alibi else jnp.zeros((h, 128), jnp.float32))
    qs = q * jnp.asarray(scale, q.dtype)
    axes = tuple(vary_axes) if vary_axes else (axis_name,)
    return _ring_flash_local(qs, k, v, seg, slopes_arr, axis_name, window,
                             use_alibi, int(block_q), int(block_k), axes)


def ring_flash_supported(sq_local, sk_local, d, window, block_q=DEFAULT_BLOCK_Q,
                         block_k=DEFAULT_BLOCK_K) -> bool:
    """Static eligibility: shard sizes must tile, head dim must be MXU-
    friendly, and the window must be a static int (traced windows fall back
    to the einsum ring)."""
    bq = min(block_q, sq_local)
    bk = min(block_k, sk_local)
    if sq_local % bq or sk_local % bk:
        return False
    if d not in (64, 128, 256):
        return False
    if window is not None and not isinstance(window, int):
        return False
    return True
