"""DeepSpeed-TPU: a TPU-native training & inference framework.

A from-scratch JAX/XLA/Pallas re-design of the capability set of DeepSpeed
(reference ``deepspeed/__init__.py``): ``initialize()`` brings up a device
mesh and returns an engine with forward/backward/step and checkpoint APIs;
ZeRO stages map to parameter/gradient/optimizer-state sharding over the mesh's
data axes; pipeline/tensor/sequence/expert parallelism ride named mesh axes
with XLA collectives over ICI/DCN.
"""

__version__ = "0.1.0"
version = __version__

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .comm.comm import init_distributed  # noqa: F401
from .runtime.config import DeepSpeedConfig  # noqa: F401
from .utils import logger  # noqa: F401

# Public subsystem namespaces (reference: deepspeed.zero / deepspeed.pipe /
# deepspeed.moe / deepspeed.checkpointing)
from .runtime import zero  # noqa: F401
from .runtime import pipe  # noqa: F401
from .runtime.pipe import PipelineModule, LayerSpec, TiedLayerSpec  # noqa: F401
from .runtime.activation_checkpointing import checkpointing  # noqa: F401
from . import moe  # noqa: F401
from . import module_inject  # noqa: F401


def _resolve_zero_subgroups(ds_config):
    """Translate MiCS / ZeRO++ hpZ config into a zrep × data mesh split.

    ``mics_shard_size`` k (reference ``runtime/zero/mics.py:64``): params and
    optimizer shard over groups of k devices, replicate across groups.
    ``zero_hpz_partition_size`` s (reference ``groups.py:529``): params keep a
    within-group secondary partition of size s while optimizer state shards
    over the full data-parallel world.
    """
    from .utils import groups as _groups

    zc = ds_config.zero_config
    mics = zc.mics_shard_size if zc.mics_shard_size and zc.mics_shard_size > 0 else 0
    hpz = zc.zero_hpz_partition_size if zc.zero_hpz_partition_size > 1 else 0
    if not mics and not hpz:
        return
    if mics and hpz:
        raise ValueError("mics_shard_size and zero_hpz_partition_size are mutually exclusive")
    sub = mics or hpz
    mc = ds_config.mesh
    if _groups.mesh_is_initialized():
        mesh = _groups.get_mesh()
        if mesh.shape["data"] != sub:
            raise ValueError(
                f"mesh already initialized with data={mesh.shape['data']}, "
                f"zrep={mesh.shape['zrep']} — rebuild it with data={sub} and "
                f"zrep=dp/{sub} to use "
                f"{'mics_shard_size' if mics else 'zero_hpz_partition_size'}={sub}")
        return
    import jax
    n = len(jax.devices())
    fixed = mc.tensor * mc.pipe * mc.seq * mc.expert
    dp_total = mc.data if isinstance(mc.data, int) and mc.data > 0 else n // fixed
    if dp_total % sub != 0:
        raise ValueError(f"data-parallel world {dp_total} not divisible by subgroup size {sub}")
    mc.data = sub
    mc.zrep = dp_total // sub


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=29500,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               mesh_param=None,
               config_params=None):
    """Initialize the DeepSpeed-TPU engine. Analog of ``deepspeed/__init__.py:69``.

    Arguments:
        model: a model definition — any object exposing ``init(rng, *batch)``
            and ``apply(params, *batch)`` (see ``deepspeed_tpu.models``), or a
            flax ``nn.Module`` (adapted automatically), or a ready param pytree
            paired with an apply function via ``models.FunctionalModel``.
        optimizer: optional optimizer name/instance overriding the config.
        config: DeepSpeed-style JSON config (dict, path, or JSON string).

    Returns: tuple of ``engine, optimizer, training_dataloader, lr_scheduler``
    """
    from .runtime.engine import DeepSpeedEngine

    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    assert model is not None, "deepspeed_tpu.initialize requires a model"

    ds_config = None if config is None else DeepSpeedConfig(config)
    if ds_config is not None:
        _resolve_zero_subgroups(ds_config)
    init_distributed(distributed_port=distributed_port, verbose=False,
                     mesh_config=None if ds_config is None else ds_config.mesh)
    if ds_config is not None and ds_config.world_size is None:
        from .utils import groups
        ds_config._configure_train_batch_size(groups.get_data_parallel_world_size())
        ds_config.world_size = groups.get_data_parallel_world_size()
    config = ds_config if ds_config is not None else config

    engine = DeepSpeedEngine(args=args,
                             model=model,
                             optimizer=optimizer,
                             model_parameters=model_parameters,
                             training_data=training_data,
                             lr_scheduler=lr_scheduler,
                             mpu=mpu,
                             collate_fn=collate_fn,
                             config=config)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Initialize an inference engine. Analog of ``deepspeed/__init__.py:291``."""
    from .inference.config import DeepSpeedInferenceConfig
    from .inference.engine import InferenceEngine

    if config is None:
        config = {}
    if isinstance(config, dict):
        config.update(kwargs)
        config = DeepSpeedInferenceConfig(**config)
    return InferenceEngine(model, config)


def default_inference_config():
    from .inference.config import DeepSpeedInferenceConfig
    return DeepSpeedInferenceConfig().model_dump()


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args. Analog of ``__init__.py:268``."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag to ease transition)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed-TPU json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true", help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse
    return argparse.SUPPRESS
